"""REAL two-process multi-host training (the DCN-analog path).

``test_multihost.py`` unit-tests the ``initialize`` env gate; this test
actually forms a 2-process ``jax.distributed`` world over localhost —
the closest single-machine analog of a TPU pod's one-process-per-host
layout — and runs the framework's jitted DiLoCo step across it:
cross-process XLA collectives, per-process data loading
(``multihost.global_batch``), addressable-shard metric fetch
(``multihost.local_values``).

Oracle: the 2-process run must produce exactly the same per-node loss
trajectory as the same config in one process (SPMD semantics do not
depend on the process layout — the property the reference cannot test,
since its Gloo world IS its process layout).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _reference_losses():
    """Same config as tests/_multihost_worker.py, one process, 2 devices."""
    import jax

    from gym_tpu.models.base import LossModel
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.parallel.mesh import NodeRuntime
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.train_node import make_init_fn, make_train_step

    num_nodes = 2
    runtime = NodeRuntime.create(num_nodes, jax.devices()[:2])
    cfg = GPTConfig(block_size=8, vocab_size=32, n_layer=1, n_head=2,
                    n_embd=16, dropout=0.0, bias=True)
    loss_model = LossModel(GPT(cfg))
    strategy = DiLoCoStrategy(OptimSpec("adamw", lr=1e-3), H=1)
    strategy.finalize(max_steps=3)

    rng = np.random.default_rng(7)
    all_batches = rng.integers(
        0, cfg.vocab_size, (3, num_nodes, 1, 2, cfg.block_size),
        dtype=np.int64,
    )
    example = (all_batches[0, 0, 0], all_batches[0, 0, 0])
    init_fn = make_init_fn(loss_model, strategy, example, seed=0)
    state = runtime.init_state(init_fn)
    step = runtime.compile(make_train_step(loss_model, strategy, runtime.ctx))

    out = []
    for t in range(3):
        batch = runtime.shard_batch(
            (all_batches[t], np.roll(all_batches[t], -1, -1))
        )
        state, metrics = step(state, batch)
        out.append(np.asarray(metrics["loss"]))
    return np.stack(out)  # [steps, nodes]


def test_global_batch_matches_shard_batch_on_multi_axis_mesh():
    """Single-process oracle for ``multihost.global_batch``: on a
    ('node','model') mesh it must replicate rows over the tp axis and
    reproduce exactly what ``runtime.shard_batch`` builds from the same
    global data."""
    import jax

    from gym_tpu.parallel import multihost
    from gym_tpu.parallel.mesh import NodeRuntime

    runtime = NodeRuntime.create(4, jax.devices()[:8], tp=2)
    assert runtime.n_phys == 4 and runtime.tp == 2
    rng = np.random.default_rng(0)
    data = rng.normal(size=(4, 3)).astype(np.float32)

    via_global = multihost.global_batch(runtime, data)  # owns all nodes
    via_shard = runtime.shard_batch(data)
    np.testing.assert_array_equal(np.asarray(via_global),
                                  np.asarray(via_shard))
    assert via_global.sharding.is_equivalent_to(via_shard.sharding, 2)
    np.testing.assert_array_equal(multihost.local_values(via_global), data)


def test_two_process_world_matches_single_process():
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # one CPU device per process (conftest forces 8)
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=env, cwd=repo, text=True,
        )
        for pid in (0, 1)
    ]
    results = {}
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            row = json.loads(out.strip().splitlines()[-1])
            results[row["pid"]] = row["losses"]
    finally:
        for p in procs:  # don't orphan the peer on failure/timeout
            if p.poll() is None:
                p.kill()

    ref = _reference_losses()
    # process p's local node is node p of the single-process run
    for pid in (0, 1):
        np.testing.assert_allclose(
            results[pid], ref[:, pid], rtol=1e-5, atol=1e-6,
        )
    # and the runs genuinely trained (loss changed over steps)
    assert abs(ref[0, 0] - ref[-1, 0]) > 1e-4
