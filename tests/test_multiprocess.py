"""REAL two-process multi-host training (the DCN-analog path).

``test_multihost.py`` unit-tests the ``initialize`` env gate; this test
actually forms a 2-process ``jax.distributed`` world over localhost —
the closest single-machine analog of a TPU pod's one-process-per-host
layout — and runs the framework's jitted DiLoCo step across it:
cross-process XLA collectives, per-process data loading
(``multihost.global_batch``), addressable-shard metric fetch
(``multihost.local_values``).

Oracle: the 2-process run must produce exactly the same per-node loss
trajectory as the same config in one process (SPMD semantics do not
depend on the process layout — the property the reference cannot test,
since its Gloo world IS its process layout).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# jax 0.4.x's CPU backend has no multi-process array support at all
# ("Multiprocess computations aren't implemented on the CPU backend" at
# the first non-addressable device_put) — these worlds need jax >= 0.5.
import jax

needs_multiprocess_cpu = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="multi-process CPU arrays need jax >= 0.5")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _reference_losses():
    """Same config as tests/_multihost_worker.py, one process, 2 devices."""
    import jax

    from gym_tpu.models.base import LossModel
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.parallel.mesh import NodeRuntime
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.train_node import make_init_fn, make_train_step

    num_nodes = 2
    runtime = NodeRuntime.create(num_nodes, jax.devices()[:2])
    cfg = GPTConfig(block_size=8, vocab_size=32, n_layer=1, n_head=2,
                    n_embd=16, dropout=0.0, bias=True)
    loss_model = LossModel(GPT(cfg))
    strategy = DiLoCoStrategy(OptimSpec("adamw", lr=1e-3), H=1)
    strategy.finalize(max_steps=3)

    rng = np.random.default_rng(7)
    all_batches = rng.integers(
        0, cfg.vocab_size, (3, num_nodes, 1, 2, cfg.block_size),
        dtype=np.int64,
    )
    example = (all_batches[0, 0, 0], all_batches[0, 0, 0])
    init_fn = make_init_fn(loss_model, strategy, example, seed=0)
    state = runtime.init_state(init_fn)
    step = runtime.compile(make_train_step(loss_model, strategy, runtime.ctx))

    out = []
    for t in range(3):
        batch = runtime.shard_batch(
            (all_batches[t], np.roll(all_batches[t], -1, -1))
        )
        state, metrics = step(state, batch)
        out.append(np.asarray(metrics["loss"]))
    return np.stack(out)  # [steps, nodes]


def test_global_batch_matches_shard_batch_on_multi_axis_mesh():
    """Single-process oracle for ``multihost.global_batch``: on a
    ('node','model') mesh it must replicate rows over the tp axis and
    reproduce exactly what ``runtime.shard_batch`` builds from the same
    global data."""
    import jax

    from gym_tpu.parallel import multihost
    from gym_tpu.parallel.mesh import NodeRuntime

    runtime = NodeRuntime.create(4, jax.devices()[:8], tp=2)
    assert runtime.n_phys == 4 and runtime.tp == 2
    rng = np.random.default_rng(0)
    data = rng.normal(size=(4, 3)).astype(np.float32)

    via_global = multihost.global_batch(runtime, data)  # owns all nodes
    via_shard = runtime.shard_batch(data)
    np.testing.assert_array_equal(np.asarray(via_global),
                                  np.asarray(via_shard))
    assert via_global.sharding.is_equivalent_to(via_shard.sharding, 2)
    np.testing.assert_array_equal(multihost.local_values(via_global), data)


def _run_two_process(worker_name: str, extra_args=(), scratch="/tmp"):
    """Launch the worker twice; stdout/stderr go to FILES (a filled PIPE
    buffer would block one worker mid-collective and deadlock the
    lockstep pair) and the full stderr is surfaced on failure."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # one CPU device per process (conftest forces 16)
    worker = os.path.join(os.path.dirname(__file__), worker_name)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    logs = {}
    procs = []
    for pid in (0, 1):
        out_f = open(os.path.join(scratch, f"worker{pid}.out"), "w+")
        err_f = open(os.path.join(scratch, f"worker{pid}.err"), "w+")
        logs[pid] = (out_f, err_f)
        procs.append(subprocess.Popen(
            [sys.executable, "-u", worker, str(port), str(pid),
             *extra_args],
            stdout=out_f, stderr=err_f, env=env, cwd=repo, text=True,
        ))
    results = {}
    try:
        for pid, p in enumerate(procs):
            p.wait(timeout=540)
            out_f, err_f = logs[pid]
            out_f.seek(0)
            err_f.seek(0)
            out, err = out_f.read(), err_f.read()
            assert p.returncode == 0, \
                f"worker {pid} failed:\n{err[-6000:]}"
            row = json.loads(out.strip().splitlines()[-1])
            results[row["pid"]] = row
    finally:
        for p in procs:  # don't orphan the peer on failure/timeout
            if p.poll() is None:
                p.kill()
        for out_f, err_f in logs.values():
            out_f.close()
            err_f.close()
    return results


def _reference_fit_histories(tmp: str):
    """The worker's exact fit config, one process, 2 of the local CPU
    devices — the oracle the 2-process ``Trainer.fit`` must reproduce."""
    import numpy as np

    from gym_tpu.data.gpt_datasets import ContiguousGPTTrainDataset
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.trainer import Trainer

    rng = np.random.default_rng(7)
    data = rng.integers(0, 32, 2048, dtype=np.int64)
    ds = ContiguousGPTTrainDataset(data, block_size=8)
    cfg = GPTConfig(block_size=8, vocab_size=32, n_layer=1, n_head=2,
                    n_embd=16, dropout=0.0, bias=True)
    return Trainer(GPT(cfg), ds, ds).fit(
        strategy=DiLoCoStrategy(OptimSpec("adamw", lr=1e-3), H=2),
        num_nodes=2, max_steps=4, batch_size=4, minibatch_size=2,
        val_size=4, val_interval=2, device="cpu", devices=[0, 1],
        checkpoint_interval=2, save_dir=tmp + "/ckpt", run_name="mh",
        log_dir=tmp + "/logs", show_progress=False, seed=3,
    )


@pytest.mark.slow
@needs_multiprocess_cpu
def test_two_process_trainer_fit_matches_single_process(tmp_path):
    """VERDICT r3 #1: ``Trainer.fit`` ITSELF runs in a multi-process
    world — both processes call fit() unmodified and must reproduce the
    single-process run: same train/local/global loss histories, same
    averaged-parameter checksum, identical across hosts; the primary
    host's CSV matches the single-process CSV; ONE checkpoint tree is
    written (collectively), not one per rank."""
    import csv

    import numpy as np

    mh_dir = str(tmp_path / "mh")
    os.makedirs(mh_dir, exist_ok=True)
    results = _run_two_process("_multihost_fit_worker.py", (mh_dir,),
                               scratch=str(tmp_path))

    # both hosts observed the SAME run (replicated metric fetch)
    assert results[0] == {**results[1], "pid": 0}

    ref = _reference_fit_histories(str(tmp_path / "ref"))
    np.testing.assert_allclose(
        results[0]["train"], [l for _, l in ref.history["train_loss"]],
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        results[0]["local"], [l for _, l in ref.history["local_loss"]],
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        results[0]["global"], [l for _, l in ref.history["global_loss"]],
        rtol=1e-5, atol=1e-6)
    # the run genuinely trained
    assert abs(ref.history["train_loss"][0][1]
               - ref.history["train_loss"][-1][1]) > 1e-4

    def csv_losses(path):
        with open(path) as f:
            return [float(r["loss"]) for r in csv.DictReader(f)]

    # primary host's CSV == single-process CSV; non-primary wrote nothing
    mh_csv = csv_losses(os.path.join(mh_dir, "logs", "mh", "train.csv"))
    ref_csv = csv_losses(
        os.path.join(str(tmp_path / "ref"), "logs", "mh", "train.csv"))
    np.testing.assert_allclose(mh_csv, ref_csv, rtol=1e-5, atol=1e-6)
    run_dirs = os.listdir(os.path.join(mh_dir, "logs"))
    assert run_dirs == ["mh"]

    # ONE checkpoint tree, written collectively, resumable
    ckpt_root = os.path.join(mh_dir, "ckpt")
    assert os.listdir(ckpt_root) == ["mh"]
    from gym_tpu.utils.checkpoint import CheckpointManager
    mgr = CheckpointManager(ckpt_root, "mh")
    assert mgr.latest_step() == 4
    mgr.close()


@pytest.mark.slow
@needs_multiprocess_cpu
def test_two_process_world_matches_single_process():
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""  # one CPU device per process (conftest forces 8)
    worker = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            env=env, cwd=repo, text=True,
        )
        for pid in (0, 1)
    ]
    results = {}
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            row = json.loads(out.strip().splitlines()[-1])
            results[row["pid"]] = row["losses"]
    finally:
        for p in procs:  # don't orphan the peer on failure/timeout
            if p.poll() is None:
                p.kill()

    ref = _reference_losses()
    # process p's local node is node p of the single-process run
    for pid in (0, 1):
        np.testing.assert_allclose(
            results[pid], ref[:, pid], rtol=1e-5, atol=1e-6,
        )
    # and the runs genuinely trained (loss changed over steps)
    assert abs(ref[0, 0] - ref[-1, 0]) > 1e-4
