"""Multi-tenant serving (ISSUE 17): SLO classes, token quotas,
weighted-fair scheduling and preemptible decode.

Oracles:
- QUOTA DETERMINISM: the refill bucket under an injected fake clock
  admits/rejects on exact token arithmetic — typed
  ``QuotaExceededError`` (an ``AdmissionRejectedError`` subclass, so
  the whole 429 + Retry-After surface applies unchanged).
- WFQ: with multiple tenants queued, an interactive tenant's head
  beats a batch flood to the slot; the queue HEAD is still admitted
  within ``starvation_rounds`` passes (the PR-7 anti-starvation
  contract, now covering fair-queuing skips too); a single tenant
  keeps exact FCFS.
- PREEMPT-RESUME EXACTNESS: a batch request parked mid-decode for an
  interactive one resumes and finishes BYTE-IDENTICAL to an
  uncontended run — the per-token ``fold_in(base, gen_idx)`` key
  schedule makes this an equality oracle, not a tolerance.
- TYPED, NEVER SILENT: a parked request caught in an engine failover
  resolves with ``EngineFailedError`` — its future never hangs.
- WIRE/WORKER HYGIENE: ``QuotaExceededError`` survives the socket hop
  typed with its retry hint; a submit frame carrying UNKNOWN fields is
  served with a stderr note, never rejected (mixed-version fleets
  degrade soft).
"""

import threading
import time
import types

import numpy as np
import pytest

import jax

from gym_tpu.models.nanogpt import GPT, GPTConfig, generate_fast
from gym_tpu.serve import wire
from gym_tpu.serve.engine import InferenceEngine, SamplingParams
from gym_tpu.serve.scheduler import (CLASS_PRIORITY, ClassQuota,
                                     EngineFailedError,
                                     QuotaExceededError,
                                     AdmissionRejectedError,
                                     RequestStatus, Scheduler)
from gym_tpu.serve.worker import _SUBMIT_FIELDS, WorkerServer
from gym_tpu.servesim import cost_model


@pytest.fixture(scope="module")
def setup():
    cfg = GPTConfig(block_size=64, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True)
    model = GPT(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int64),
                        train=False)["params"]
    return cfg, model, params


def _prompt(n, seed, vocab=48):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed),
                                         (n,), 0, vocab))


def _drain(sched, handles, limit=5000):
    for _ in range(limit):
        if all(h.status in (RequestStatus.DONE, RequestStatus.FAILED)
               for h in handles):
            return
        sched.step()
    raise AssertionError("scheduler did not drain")


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


# -- quotas ---------------------------------------------------------------


def test_class_priority_mirrors_cost_model():
    """The sweep's jax-free cost model duplicates the scheduler's
    priority table (importing the scheduler would drag jax into the
    fast path) — this pin is what allows the duplication."""
    assert cost_model._CLASS_PRIORITY == CLASS_PRIORITY


def test_quota_refill_determinism_fake_clock(setup):
    """Exact bucket arithmetic under a stepped clock: cap = rate ×
    burst_s tokens, a dry class rejects typed with a computable
    Retry-After, and the advertised retry interval is precisely what
    refills enough budget."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2)
    clock = FakeClock()
    sched = Scheduler(
        eng, quotas={"batch": ClassQuota(tokens_per_s=10.0,
                                         burst_s=1.0)},
        quota_clock=clock)
    sp = SamplingParams(max_new_tokens=8, seed=1)
    # cap = 10; first take: 10 -> 2
    r1 = sched.submit(_prompt(8, 1), sp, slo_class="batch")
    # second take needs 8 > 2 -> typed reject, retry = (8-2)/10
    with pytest.raises(QuotaExceededError) as ei:
        sched.submit(_prompt(8, 2), sp, slo_class="batch")
    assert isinstance(ei.value, AdmissionRejectedError)
    assert ei.value.retry_after_s == pytest.approx(0.6)
    assert sched.quota_rejections == {"batch": 1}
    # other classes are not rate-limited by batch's bucket
    r3 = sched.submit(_prompt(8, 3), sp, slo_class="interactive")
    # advancing the clock past the advertised retry refills the bucket
    # (an epsilon over: the refill itself is float arithmetic)
    clock.t += ei.value.retry_after_s + 1e-3
    r4 = sched.submit(_prompt(8, 4), sp, slo_class="batch")
    _drain(sched, [r1, r3, r4])
    assert [len(r.tokens) for r in (r1, r3, r4)] == [8, 8, 8]
    snap = sched.tenant_snapshot()
    assert snap["quota_rejections"] == {"batch": 1}
    assert snap["quota_fill"]["batch"] < 0.05


def test_quota_oversize_request_passes_at_full_bucket(setup):
    """A request larger than the whole bucket is admitted when the
    bucket is FULL (level goes negative — long-run rate enforcement),
    instead of starving forever behind an unpassable bar."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2)
    clock = FakeClock()
    sched = Scheduler(
        eng, quotas={"batch": ClassQuota(tokens_per_s=4.0,
                                         burst_s=1.0)},
        quota_clock=clock)
    big = SamplingParams(max_new_tokens=16, seed=1)   # 4x the cap
    r1 = sched.submit(_prompt(8, 1), big, slo_class="batch")
    with pytest.raises(QuotaExceededError):
        sched.submit(_prompt(8, 2), big, slo_class="batch")
    _drain(sched, [r1])
    assert len(r1.tokens) == 16


def test_unknown_slo_class_rejected_typed(setup):
    """A typo'd class must fail loudly (HTTP 400), not silently map to
    some default priority — that would be an isolation hole."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2)
    sched = Scheduler(eng)
    with pytest.raises(ValueError, match="unknown slo_class"):
        sched.submit(_prompt(8, 1), SamplingParams(max_new_tokens=4),
                     slo_class="premium")


# -- weighted-fair queuing ------------------------------------------------


def test_single_tenant_keeps_fcfs_order(setup):
    """The default deployment (one tenant, unpaged engine) must keep
    the exact pre-tenant admission order: FCFS."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=1)
    sched = Scheduler(eng)
    sp = SamplingParams(max_new_tokens=4, seed=0)
    reqs = [sched.submit(_prompt(8, i), sp) for i in range(4)]
    _drain(sched, reqs)
    firsts = [r.first_token_t for r in reqs]
    assert firsts == sorted(firsts)


def test_wfq_interactive_head_beats_batch_flood(setup):
    """Two tenants queued: the interactive tenant's head (weight 8)
    carries the earliest virtual finish tag and wins the first free
    slot even though the batch flood (weight 1) queued first."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=1)
    sched = Scheduler(eng)
    flood = [sched.submit(_prompt(8, i),
                          SamplingParams(max_new_tokens=8, seed=i),
                          tenant="tenant_b", slo_class="batch")
             for i in range(6)]
    victim = sched.submit(_prompt(8, 99),
                          SamplingParams(max_new_tokens=4, seed=99),
                          tenant="tenant_a", slo_class="interactive")
    _drain(sched, flood + [victim])
    assert victim.done_t < min(b.done_t for b in flood)


def test_wfq_starvation_bound_admits_head(setup):
    """A batch head passed over by fair-queuing skips must still admit
    within ``starvation_rounds`` passes — the PR-7 anti-starvation
    contract extended to WFQ: interactive pressure cannot starve batch
    unboundedly."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=1)
    sched = Scheduler(eng, starvation_rounds=2)
    head = sched.submit(_prompt(8, 0),
                        SamplingParams(max_new_tokens=4, seed=0),
                        tenant="tenant_b", slo_class="batch")
    inter = [sched.submit(_prompt(8, 1 + i),
                          SamplingParams(max_new_tokens=4, seed=1 + i),
                          tenant="tenant_a", slo_class="interactive")
             for i in range(6)]
    _drain(sched, [head] + inter)
    # the head may lose at most starvation_rounds + 1 admissions
    later = sorted(r.done_t for r in inter)
    assert head.done_t < later[3], \
        "batch head starved past the starvation_rounds bound"


# -- preemptible decode ---------------------------------------------------


def _uncontended(params, cfg, prompt, sp, **engine_kw):
    eng = InferenceEngine(params, cfg, **engine_kw)
    slot, ev = eng.admit(prompt, sp)
    toks = [ev.token]
    while not ev.finished:
        evs = [e for e in eng.step() if e.slot == slot]
        assert evs
        ev = evs[-1]
        toks.extend(e.token for e in evs)
    return toks


def test_preempt_parks_batch_resumes_byte_identical(setup):
    """The tentpole oracle: a batch request parked mid-decode for an
    interactive arrival finishes with EXACTLY the token stream of an
    uncontended run — equality, not tolerance (the per-token
    ``fold_in(base, gen_idx)`` key schedule is position-keyed, so the
    park/resume round-trip through host memory must be invisible)."""
    cfg, model, params = setup
    kw = dict(num_slots=1, paged=True, page_size=8, kv_pages=64)
    batch_prompt = _prompt(8, 7)
    batch_sp = SamplingParams(max_new_tokens=24, temperature=0.9,
                              top_k=7, seed=7)
    ref = _uncontended(params, cfg, batch_prompt, batch_sp, **kw)

    eng = InferenceEngine(params, cfg, **kw)
    sched = Scheduler(eng, preempt=True)
    batch = sched.submit(batch_prompt, batch_sp,
                         tenant="tenant_b", slo_class="batch")
    for _ in range(200):
        sched.step()
        if len(batch.tokens) >= 4:
            break
    assert len(batch.tokens) >= 4 and batch.status is \
        RequestStatus.RUNNING
    inter = sched.submit(_prompt(8, 42),
                         SamplingParams(max_new_tokens=6, seed=42),
                         tenant="tenant_a", slo_class="interactive")
    _drain(sched, [inter, batch])
    assert sched.preemptions >= 1 and sched.resumes >= 1
    assert batch.preemptions >= 1
    # the interactive request got the slot while batch was parked
    assert inter.done_t < batch.done_t
    # byte-identical resume: the oracle
    assert batch.tokens == ref
    # and the interactive stream equals ITS uncontended run too
    assert inter.tokens == _uncontended(
        params, cfg, _prompt(8, 42),
        SamplingParams(max_new_tokens=6, seed=42), **kw)


def test_preempt_never_within_same_class(setup):
    """Preemption runs only in favor of a STRICTLY more urgent class —
    same-class traffic must never thrash slots back and forth."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=1, paged=True,
                          page_size=8, kv_pages=64)
    sched = Scheduler(eng, preempt=True)
    sp = SamplingParams(max_new_tokens=8, seed=1)
    a = sched.submit(_prompt(8, 1), sp, slo_class="batch")
    b = sched.submit(_prompt(8, 2), sp, slo_class="batch")
    _drain(sched, [a, b])
    assert sched.preemptions == 0


def test_parked_request_fails_typed_on_engine_death(setup):
    """A replica dying while holding a PARKED request (its pinned pages
    died with the engine's pool) must resolve that request's future
    typed — never a silent drop, never a hang."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=1, paged=True,
                          page_size=8, kv_pages=64)
    sched = Scheduler(eng, preempt=True)
    batch = sched.submit(_prompt(8, 7),
                         SamplingParams(max_new_tokens=24, seed=7),
                         tenant="tenant_b", slo_class="batch")
    for _ in range(200):
        sched.step()
        if len(batch.tokens) >= 2:
            break
    sched.submit(_prompt(8, 42),
                 SamplingParams(max_new_tokens=6, seed=42),
                 tenant="tenant_a", slo_class="interactive")
    for _ in range(50):
        sched.step()
        if sched.preemptions:
            break
    assert sched.preemptions >= 1
    snap = sched.tenant_snapshot()
    assert snap["parked"] == 1
    victims = sched.fail_inflight(
        EngineFailedError("engine died under chaos"))
    assert batch in victims
    with pytest.raises(EngineFailedError):
        batch.result(timeout=1.0)
    assert sched.tenant_snapshot()["parked"] == 0


# -- wire + worker hygiene ------------------------------------------------


def test_quota_error_survives_the_socket_typed():
    exc = QuotaExceededError("slo_class=batch token quota exhausted",
                             retry_after_s=2.5)
    frame = wire.exception_to_frame(7, exc)
    back = wire.frame_to_exception(
        wire.decode_payload(wire.encode_frame(frame)[4:]))
    assert type(back) is QuotaExceededError
    assert back.retry_after_s == pytest.approx(2.5)
    assert isinstance(back, AdmissionRejectedError)


def test_submit_fields_pin():
    """The worker's known-field set must cover everything the router
    sends today — adding a field to the ROUTER without teaching the
    worker produces a stderr note on every request, which this pin
    turns into a test failure instead of silent log spam."""
    assert {"type", "id", "prompt", "sampling", "prefix",
            "deadline_s", "stream", "submit_timeout", "coalesce_s",
            "tenant", "slo_class"} <= _SUBMIT_FIELDS


def test_unknown_submit_field_served_with_note(setup, capsys):
    """A submit frame carrying a field this worker has never heard of
    is served normally (ignored-with-note) — the mixed-version-fleet
    contract: an old worker behind a new router degrades soft, it does
    not reject traffic."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2)
    sched = Scheduler(eng)
    stop = threading.Event()
    driver = threading.Thread(target=sched.run, args=(stop,),
                              daemon=True)
    driver.start()
    stub = types.SimpleNamespace(scheduler=sched)
    sent = []

    def send(frame):
        sent.append(frame)
        return True

    frame = {"type": "submit", "id": "r1",
             "prompt": _prompt(8, 3).tolist(),
             "sampling": {"max_new_tokens": 5, "seed": 3},
             "tenant": "tenant_a", "slo_class": "interactive",
             "qos_hint": "gold-plated"}         # the unknown field
    try:
        WorkerServer._stream_request(stub, frame, send, {}, set(),
                                     threading.Lock())
    finally:
        stop.set()
        driver.join(timeout=10)
    err = capsys.readouterr().err
    assert "unknown fields ['qos_hint']" in err
    assert sent[0] == {"type": "accepted", "id": "r1"}
    done = [f for f in sent if f["type"] == "done"]
    assert done and done[0]["tokens_total"] == 5
    # and the stream is still exact: tenant plumbing changed nothing
    chunks = [t for f in sent if f["type"] == "chunk"
              for t in f["tokens"]]
    ref = generate_fast(params, cfg, _prompt(8, 3)[None], 5,
                        seed=3)[0, 8:].tolist()
    assert chunks == ref


# -- per-class metrics ----------------------------------------------------


def _fake_req(rid, tokens, ttft, lat, tenant=None, slo_class=None):
    return types.SimpleNamespace(
        id=rid, prompt=np.zeros(4, np.int32),
        tokens=list(range(tokens)), error=None, exception=None,
        ttft_s=ttft, avg_token_latency_s=lat,
        tenant=tenant, slo_class=slo_class)


def test_metrics_per_class_headline_and_csv_roundtrip(tmp_path):
    """``headline()`` and ``read_headline`` agree on the per-class
    breakdown: TTFT tails split by slo_class, preempt/resume event
    rows counted WITHOUT double-counting tokens (events carry a blank
    new_tokens cell; tokens land once, on the completion row)."""
    from gym_tpu.serve.metrics import ServeMetrics, read_headline
    m = ServeMetrics(str(tmp_path))
    for i in range(1, 11):
        m.request_done(
            _fake_req(i, 4, i / 100.0, 0.01,
                      tenant="tenant_a", slo_class="interactive"),
            queue_depth=0, active_slots=1)
    batch = _fake_req(99, 8, 0.5, 0.01, tenant="tenant_b",
                      slo_class="batch")
    m.request_preempted(batch, queue_depth=1, active_slots=1)
    m.request_resumed(batch, queue_depth=0, active_slots=1)
    m.request_done(batch, queue_depth=0, active_slots=1)
    m.request_rejected(queue_depth=0, active_slots=1,
                       tenant="tenant_b", slo_class="batch")
    head = m.headline()
    assert head["requests_done"] == 11
    assert head["requests_preempted"] == 1
    assert head["requests_resumed"] == 1
    cls = head["classes"]
    assert cls["interactive"]["requests_done"] == 10
    assert cls["interactive"]["ttft_p99_s"] == pytest.approx(0.0991)
    assert cls["batch"]["preemptions"] == 1
    assert cls["batch"]["resumes"] == 1
    assert cls["batch"]["requests_rejected"] == 1
    m.close()
    disk = read_headline(str(tmp_path / "serve.csv"))
    assert disk["requests_done"] == 11
    assert disk["requests_preempted"] == 1
    assert disk["requests_resumed"] == 1
    # tokens counted once: 10x4 interactive + 8 batch
    assert disk["tokens_out"] == 48
    dcls = disk["classes"]
    assert dcls["interactive"]["requests_done"] == 10
    assert dcls["interactive"]["ttft_p99_s"] == pytest.approx(0.0991)
    assert dcls["batch"]["preemptions"] == 1
    assert dcls["batch"]["requests_rejected"] == 1


def test_metrics_single_tenant_headline_has_no_classes_block(tmp_path):
    """The single-tenant default emits NO classes block — dashboards
    reading the pre-tenant headline see the pre-tenant shape."""
    from gym_tpu.serve.metrics import ServeMetrics, read_headline
    m = ServeMetrics(str(tmp_path))
    m.request_done(_fake_req(1, 4, 0.1, 0.01), queue_depth=0,
                   active_slots=1)
    head = m.headline()
    assert "classes" not in head
    m.close()
    assert "classes" not in read_headline(str(tmp_path / "serve.csv"))
