"""Elastic ZeRO (ROADMAP: resume-at-any-node-count).

Property layer: K→K'→K redistribution is BIT-identical for params AND
optimizer state — including the zero pad tail of the flat ZeRO slices —
over uneven K' (shard sizes that do not divide n) and for both
checkpoint layouts (stacked and ZeRO-2 sharded). Redistributions are
registry programs: a second reshard at the same (K→K', shapes)
signature must compile NOTHING (warm registry — the zero-recompile
resume gate). The sharded layout's bytes are O(model/K) per node, the
typed ``NodeCountMismatchError`` fires both at the strategy step (a
K'-sized shard fed to a K mesh) and at reshard time (genuinely per-node
state with no generic redistribution).

Integration layer: a real ``fit`` checkpointed at K resumes at K' —
including onto a vnode-folded mesh (K'=3 on 2 devices) — continuing the
CSV/step trajectory; the controller loop (``elastic_fit``) paces
segments with the serving fleet's validated ``AutoscaleController``.
"""

import os
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gym_tpu import TrainState
from gym_tpu.elastic import (STACKED_LAYOUT, ZERO2_LAYOUT,
                             ElasticTrainController, cold_restart_events,
                             elastic_fit, elastic_meta, make_zero2_codec,
                             param_leaf_specs, reshard_events,
                             reshard_state, saved_state_template)
from gym_tpu.programs import compile_counter
from gym_tpu.programs.elastic_defs import elastic_shard_size
from gym_tpu.strategy import (NodeCountMismatchError, OptimSpec,
                              ZeroReduceStrategy)
from gym_tpu.strategy.base import StrategyLifecycleError

N = 11  # 5 + 3*2 params — odd, so every K in play pads the last shard


def _flat(params_row):
    """The concatenated raveled vector in tree-leaf order (the ZeRO
    shard order)."""
    return np.concatenate(
        [np.asarray(x).ravel() for x in jax.tree.leaves(params_row)])


def _mk_state(k, seed=0, step=6):
    """A synthetic K-node zero-strategy state: replicated params, flat
    [K, ceil(N/K)] moments with an all-zero pad tail, canonical per-node
    rng (``fold_in(key, i+1)`` — the trainer's derivation)."""
    rng = np.random.default_rng(seed)
    b = rng.normal(size=(5,)).astype(np.float32)
    w = rng.normal(size=(3, 2)).astype(np.float32)
    params = {"b": jnp.asarray(np.repeat(b[None], k, 0)),
              "w": jnp.asarray(np.repeat(w[None], k, 0))}
    s = elastic_shard_size(N, k)

    def shard_vec(v):
        pad = np.zeros(k * s, np.float32)
        pad[:N] = v
        return jnp.asarray(pad.reshape(k, s))

    mu = rng.normal(size=(N,)).astype(np.float32)
    nu = np.abs(rng.normal(size=(N,))).astype(np.float32)
    keys = jax.vmap(
        lambda i: jax.random.key_data(
            jax.random.fold_in(jax.random.PRNGKey(3), i + 1))
    )(jnp.arange(k))
    return TrainState(
        params=params,
        model_state={},
        strategy_state={"opt": {"count": jnp.full((k,), step, jnp.int32),
                                "mu": shard_vec(mu), "nu": shard_vec(nu)}},
        step=jnp.full((k,), step, jnp.int32),
        rng=keys,
    )


def _assert_states_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("k_mid", [3, 5])
def test_reshard_roundtrip_bit_identical_stacked(k_mid):
    """K→K'→K over the stacked layout: params re-replicated, flat
    moments re-partitioned — every leaf bit-identical on return,
    including the pad tail (zero by the AdamW invariant: pad moments
    start 0 and mu=nu=0 updates to 0)."""
    k = 4
    saved = _mk_state(k)
    meta_k = elastic_meta(k, STACKED_LAYOUT, N)
    mid = reshard_state(saved, meta_k, _mk_state(k_mid, seed=9))
    # the mid-membership slices carry the same vector, freshly padded
    assert mid.strategy_state["opt"]["mu"].shape == (
        k_mid, elastic_shard_size(N, k_mid))
    np.testing.assert_array_equal(
        np.asarray(mid.strategy_state["opt"]["mu"]).ravel()[:N],
        np.asarray(saved.strategy_state["opt"]["mu"]).ravel()[:N])
    back = reshard_state(mid, elastic_meta(k_mid, STACKED_LAYOUT, N),
                         _mk_state(k, seed=17))
    _assert_states_equal(back, saved)


@pytest.mark.parametrize("k_mid", [3, 5])
def test_reshard_roundtrip_bit_identical_zero2(k_mid):
    """The same round-trip through the ZeRO-2 checkpoint layout: shard
    with the codec at K, reshard the raw sharded tree onto K', then
    back — params AND moments bit-identical (f32 staging is lossless
    for f32 params)."""
    k = 4
    saved = _mk_state(k)
    to_canon, from_canon = make_zero2_codec(saved, k)
    raw = jax.device_get(to_canon(saved))
    # the codec round-trips exactly on its own
    _assert_states_equal(from_canon(raw), saved)
    # sharded params really are O(model/K) per node: [K, ceil(N/K)] f32
    assert raw["zero2"]["param_shards"].shape == (k, elastic_shard_size(N, k))
    mid = reshard_state(raw, elastic_meta(k, ZERO2_LAYOUT, N),
                        _mk_state(k_mid, seed=9))
    back = reshard_state(mid, elastic_meta(k_mid, STACKED_LAYOUT, N),
                         _mk_state(k, seed=17))
    _assert_states_equal(back, saved)


def test_reshard_registry_warm_zero_recompiles():
    """A second reshard at the same (K→K', shapes) signature acquires
    every program from the registry — zero new builds (the re-resume
    gate in ``scripts/ci_elastic.sh`` asserts the same end to end)."""
    saved = _mk_state(4)
    meta = elastic_meta(4, STACKED_LAYOUT, N)
    reshard_state(saved, meta, _mk_state(3, seed=9))
    warm = compile_counter()
    reshard_state(saved, meta, _mk_state(3, seed=23))
    assert compile_counter() == warm


def test_reshard_rejects_per_node_state():
    """State whose rows genuinely differ across nodes (e.g. a mid-cycle
    error-feedback residual) has no generic redistribution — typed
    error, not silent corruption."""
    assert issubclass(NodeCountMismatchError, StrategyLifecycleError)
    saved = _mk_state(4)
    per_node = saved.replace(model_state={
        "residual": jnp.arange(4 * 5, dtype=jnp.float32).reshape(4, 5)})
    target = _mk_state(3, seed=9).replace(
        model_state={"residual": jnp.zeros((3, 5), jnp.float32)})
    with pytest.raises(NodeCountMismatchError, match="rows differ"):
        reshard_state(per_node, elastic_meta(4, STACKED_LAYOUT, N), target)


def test_zero_step_rejects_mismatched_shard():
    """Satellite: feeding a K'-sized optimizer shard to a K-node step
    raises the typed error naming both sizes (instead of a shape error
    deep inside the all-gather)."""
    from gym_tpu.parallel import NodeRuntime

    k = 4
    strat = ZeroReduceStrategy(OptimSpec("adamw", lr=0.01))
    rt = NodeRuntime.create(k, None)
    strat.finalize(10)
    strat.bind_ctx(rt.ctx)
    w0 = {"w": np.zeros((k, 7, 3), np.float32),
          "b": np.zeros((k, 5), np.float32)}   # n=26: s(K=4)=7, s(K=3)=9
    params = rt.shard_batch(w0)
    state = rt.compile(lambda p: strat.init(p), donate_state=False)(params)
    stale = jax.tree.map(
        lambda x: (jnp.pad(x, ((0, 0), (0, 2)))
                   if getattr(x, "ndim", 0) == 2 and x.shape[-1] == 7
                   else x), state)
    step = rt.compile(
        lambda p, s, g, t: strat.step(g, p, s, t, rt.ctx),
        donate_state=False)
    tvec = rt.shard_batch(np.zeros(k, np.int32))
    with pytest.raises(NodeCountMismatchError, match="num_nodes=4"):
        step(params, stale, params, tvec)


def test_saved_state_template_shapes():
    """The restore template describes the checkpoint AS SAVED (K rows,
    saved-shard widths, numpy leaves) while keeping the live tree
    structure — the combination that avoids both Orbax's device-topology
    pin and the namedtuple→dict structure loss."""
    target = _mk_state(3, seed=9)
    tpl = saved_state_template(target, elastic_meta(4, STACKED_LAYOUT, N))
    assert isinstance(tpl, TrainState)
    assert tpl.params["b"].shape == (4, 5)
    assert tpl.strategy_state["opt"]["mu"].shape == (
        4, elastic_shard_size(N, 4))
    assert all(isinstance(x, np.ndarray) for x in jax.tree.leaves(tpl))
    z = saved_state_template(target, elastic_meta(4, ZERO2_LAYOUT, N))
    assert z["zero2"]["param_shards"].shape == (4, elastic_shard_size(N, 4))
    # saved=None (pre-elastic checkpoint): stacked at the live K
    legacy = saved_state_template(target, None)
    assert legacy.step.shape == (3,)


def test_zero2_ckpt_bytes_o_model_over_k():
    """The sharded checkpoint stores ceil(n/K) f32 per node for params
    (plus the already-sharded moments) — total O(model), i.e. per-node
    O(model/K) — where the stacked layout stores K full replicas."""
    k = 4
    saved = _mk_state(k)
    to_canon, _ = make_zero2_codec(saved, k)
    raw = jax.device_get(to_canon(saved))

    def nbytes(tree):
        return sum(np.asarray(x).nbytes for x in jax.tree.leaves(tree))

    stacked_params = nbytes(saved.params)          # K * n * 4
    sharded_params = nbytes(raw["zero2"]["param_shards"])
    assert stacked_params == k * N * 4
    assert sharded_params == k * elastic_shard_size(N, k) * 4  # ~ n * 4
    assert sharded_params <= stacked_params / k + k * 4
    # moments were already 1/K shards; the codec passes them through
    assert (nbytes(raw["zero2"]["strategy_state"])
            == nbytes(saved.strategy_state))


def test_reshard_vs_cold_restart_events():
    """The analytic pricing the sweep uses: a reshard moves ~3 model
    vectors of bytes through all_gathers; a cold restart re-broadcasts
    the same volume AND recomputes lost steps (priced by the caller)."""
    ev = reshard_events(N, 4, 3)
    assert [e.op for e in ev] == ["all_gather", "all_gather"]
    assert sum(e.bytes for e in ev) == 3 * 4 * N
    assert all(e.group == 4 for e in ev)
    cold = cold_restart_events(N, 3)
    assert [e.op for e in cold] == ["broadcast"]
    assert cold[0].bytes == 3 * 4 * N and cold[0].group == 3


def test_controller_bounded_scale_up_and_down():
    """The serving fleet's controller drives training membership: two
    over-watermark ticks (up_patience) add a node, bounded by k_max;
    drained backlog eventually retires down to k_min."""
    c = ElasticTrainController(k_min=1, k_max=3)
    assert c.tick(num_nodes=2, backlog_tokens=1e6, tokens_per_s=10.0) == 2
    assert c.tick(num_nodes=2, backlog_tokens=1e6, tokens_per_s=10.0) == 3
    assert "scale up" in c.last_reason or "drain" in c.last_reason
    # at the ceiling the controller can only hold
    for _ in range(8):
        k = c.tick(num_nodes=3, backlog_tokens=1e6, tokens_per_s=10.0)
        assert k == 3


def test_elastic_fit_paces_segments_through_resume():
    """``elastic_fit`` runs max_steps in resume="auto" segments and
    records the controller's decision trail; every fit call carries the
    membership the controller chose."""
    calls = []

    class Stub:
        def fit(self, **kw):
            calls.append(kw)
            return SimpleNamespace(steps=kw["max_steps"], preempted=False)

    hist, res = elastic_fit(
        Stub(), controller=ElasticTrainController(k_min=1, k_max=4),
        num_nodes=2, max_steps=9, segment_steps=3, tokens_per_step=16,
        save_dir="/tmp/_elastic_fit_stub")
    assert res.steps == 9 and len(calls) == len(hist) == 3
    assert [c["max_steps"] for c in calls] == [3, 6, 9]
    assert all(c["resume"] == "auto" for c in calls)
    assert [h["nodes"] for h in hist] == [c["num_nodes"] for c in calls]
    with pytest.raises(ValueError, match="save_dir"):
        elastic_fit(Stub(), controller=ElasticTrainController(),
                    num_nodes=1, max_steps=1, segment_steps=1,
                    tokens_per_step=1)


def _fit_workload():
    import flax.linen as nn
    import optax

    from gym_tpu import Trainer
    from gym_tpu.data import ArrayDataset

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, batch, train=True):
            x, y = batch
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(16)(x))
            return optax.softmax_cross_entropy_with_integer_labels(
                nn.Dense(10)(x).astype(jnp.float32), y).mean()

    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, size=128).astype(np.int32)
    x = rng.normal(0, 0.3, size=(128, 8, 8)).astype(np.float32)
    for i, y in enumerate(labels):
        x[i, y % 8, :] += 1.5
    return Trainer(Tiny(), ArrayDataset(x, labels))


def test_fit_resume_at_new_node_count_vnode(tmp_path):
    """End to end: a zero2-checkpointed K=2 run resumes at K=3 on TWO
    devices — the new membership only exists as a vnode folding — and
    the step/CSV trajectory continues across the change (cum_comm_bytes
    monotone, no step replayed)."""
    t = _fit_workload()
    common = dict(batch_size=16, minibatch_size=8, val_interval=0,
                  show_progress=False, seed=3, checkpoint_interval=2,
                  save_dir=str(tmp_path / "ckpt"), run_name="el",
                  log_dir=str(tmp_path / "logs"), async_checkpoint=False,
                  devices=[0, 1])
    mk = lambda: ZeroReduceStrategy(OptimSpec("adamw", lr=0.05))
    r1 = t.fit(strategy=mk(), num_nodes=2, max_steps=4, **common)
    assert r1.steps == 4
    r2 = t.fit(strategy=mk(), num_nodes=3, max_steps=6, resume="auto",
               **common)
    assert r2.steps == 6
    assert r2.history["train_loss"][0][0] == 4  # resumed, not replayed
    csv = (tmp_path / "logs" / "el" / "train.csv").read_text().splitlines()
    steps = [int(r.split(",")[0]) for r in csv[1:]]
    cum = [int(r.split(",")[-1]) for r in csv[1:]]
    assert steps == list(range(6))
    assert cum == sorted(cum) and len(set(cum)) == len(cum)
