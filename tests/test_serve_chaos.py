"""Serving under fire (ISSUE 5): deadlines, load shedding, quarantine,
and the self-healing engine supervisor.

Acceptance oracles pinned here:

- **deadline oracle** — a request whose ``deadline_s`` is shorter than
  the EWMA-estimated service time is rejected AT ADMISSION (typed,
  ``retry_after_s`` hint, never enqueued) while a feasible request
  submitted concurrently still completes; an already-queued request past
  its deadline is shed BEFORE prefill, and a running one is cancelled at
  the chunk boundary with its slot freed.
- **NaN quarantine oracle** — an injected NaN in one slot's KV cache
  fails only that slot's request (typed ``SlotQuarantinedError``); a
  concurrent request in a neighbor slot returns tokens IDENTICAL to an
  uncontended ``generate_fast`` run.
- **supervisor oracle** — with ``serve.decode`` faults injected (raise
  or hang) the supervisor fails in-flight requests typed, rebuilds the
  engine WARM (global program LRUs) and resumes the queue; a wedged
  driver thread that eventually wakes is discarded by the scheduler
  epoch instead of corrupting the new generation (post-recovery tokens
  still match ``generate_fast`` exactly).

The HTTP tests drive the REAL entry point (``create_server`` — the same
stack ``python -m gym_tpu.serve`` runs) in-process on an ephemeral port.
"""

import json
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gym_tpu.models.nanogpt import GPT, GPTConfig, generate_fast
from gym_tpu.serve.engine import InferenceEngine, SamplingParams
from gym_tpu.serve.metrics import ServeMetrics, read_headline
from gym_tpu.serve.scheduler import (AdmissionRejectedError,
                                     DeadlineExceededError,
                                     EngineFailedError, QueueFullError,
                                     RequestStatus, Scheduler,
                                     SchedulerClosedError,
                                     SlotQuarantinedError)
from gym_tpu.serve.supervisor import Supervisor
from gym_tpu.utils.resilience import FAULT_SITES, InjectedFault, faults


@pytest.fixture(scope="module")
def setup():
    cfg = GPTConfig(block_size=64, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True)
    model = GPT(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init({"params": rng}, np.zeros((1, 8), np.int64),
                        train=False)["params"]
    return cfg, model, params


@pytest.fixture(autouse=True)
def _clean_faults():
    """Every test starts and ends with an empty fault registry — the
    registry is process-global and a leaked rule would poison neighbors."""
    faults.reset()
    yield
    faults.reset()


def _prompt(n, seed, vocab=48):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed), (n,),
                                         0, vocab))


def _drain(sched, handles, limit=5000):
    for _ in range(limit):
        if all(h.status in (RequestStatus.DONE, RequestStatus.FAILED)
               for h in handles):
            return
        sched.step()
    raise AssertionError("scheduler did not drain")


# -- fault sites ----------------------------------------------------------


def test_serve_fault_sites_registered():
    for site in ("serve.prefill", "serve.decode", "serve.admit",
                 "serve.http"):
        assert site in FAULT_SITES
    faults.configure("serve.decode:hang=5@2,serve.admit:oserror@1-3")
    assert faults.active
    faults.reset()


def test_prefill_fault_fails_only_its_request(setup):
    """An injected IO error at the prefill site fails THAT request typed
    and the loop keeps serving — isolation, not collapse."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2)
    sched = Scheduler(eng, max_queue=8)
    faults.install("serve.prefill", "oserror", first=1, last=1)
    bad = sched.submit(_prompt(5, 0), SamplingParams(max_new_tokens=4))
    good = sched.submit(_prompt(6, 1), SamplingParams(max_new_tokens=4))
    _drain(sched, [bad, good])
    with pytest.raises(InjectedFault):
        bad.result(timeout=1)
    assert len(good.result(timeout=1)) == 4


def test_admit_fault_surfaces_at_submit(setup):
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2)
    sched = Scheduler(eng, max_queue=8)
    faults.install("serve.admit", "oserror", first=1, last=1)
    with pytest.raises(InjectedFault):
        sched.submit(_prompt(4, 0), SamplingParams(max_new_tokens=2))
    # the fault window closed — the next submit serves normally
    h = sched.submit(_prompt(4, 0), SamplingParams(max_new_tokens=2))
    _drain(sched, [h])
    assert len(h.result(timeout=1)) == 2


# -- scheduler shutdown semantics (satellite) -----------------------------


def test_submit_after_shutdown_typed_and_idempotent(setup):
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=1)
    sched = Scheduler(eng, max_queue=4)
    queued = sched.submit(_prompt(4, 0), SamplingParams(max_new_tokens=4))
    sched.shutdown(finish_running=False)
    with pytest.raises(SchedulerClosedError):
        sched.submit(_prompt(4, 1), SamplingParams(max_new_tokens=2))
    # the typed error still satisfies legacy RuntimeError handlers
    with pytest.raises(RuntimeError, match="shutting down"):
        sched.submit(_prompt(4, 1), SamplingParams(max_new_tokens=2))
    with pytest.raises(SchedulerClosedError):
        queued.result(timeout=1)
    # idempotent: a second shutdown returns immediately, no re-drain
    t0 = time.perf_counter()
    sched.shutdown(finish_running=True, deadline_s=60.0)
    assert time.perf_counter() - t0 < 1.0


def test_shutdown_drain_survives_broken_engine(setup):
    """A persistent engine fault racing the graceful drain must not kill
    the drain thread: the step exception breaks the drain loop and the
    remaining in-flight requests are failed typed — shutdown returns."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=1)
    sched = Scheduler(eng, max_queue=4)
    h = sched.submit(_prompt(4, 0), SamplingParams(max_new_tokens=10))
    sched.step()                                 # admit into the slot
    assert h.status is RequestStatus.RUNNING
    faults.install("serve.decode", "oserror")    # every dispatch fails
    sched.shutdown(finish_running=True, deadline_s=30.0)  # must not raise
    assert h.status is RequestStatus.FAILED
    with pytest.raises(SchedulerClosedError):
        h.result(timeout=1)


# -- deadlines ------------------------------------------------------------


def test_deadline_sheds_expired_queued_before_prefill(setup):
    """A queued request whose deadline passes is shed BEFORE prefill —
    even while every slot is busy (it must not wait for a free slot just
    to be told it is late)."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=1)
    sched = Scheduler(eng, max_queue=8)
    running = sched.submit(_prompt(4, 0),
                           SamplingParams(max_new_tokens=20))
    sched.step()                       # admit `running` into the one slot
    late = sched.submit(_prompt(4, 1), SamplingParams(max_new_tokens=4),
                        deadline_s=0.01)
    time.sleep(0.05)
    sched.step()                       # the shed sweep runs first
    assert late.status is RequestStatus.FAILED
    with pytest.raises(DeadlineExceededError, match="before prefill"):
        late.result(timeout=1)
    assert eng.stats.prefills == 1     # late never touched the engine
    _drain(sched, [running])
    assert len(running.result(timeout=1)) == 20


def test_deadline_cancels_running_at_chunk_boundary(setup):
    """A running request past deadline is cancelled at the next chunk
    boundary: partial tokens reported, typed error, slot freed for the
    next request."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=1, decode_chunk=2)
    sched = Scheduler(eng, max_queue=4)
    faults.install("serve.decode", "delay", arg=0.05)   # slow every chunk
    h = sched.submit(_prompt(4, 0), SamplingParams(max_new_tokens=40),
                     deadline_s=0.12)
    for _ in range(50):
        sched.step()
        if h.status is RequestStatus.FAILED:
            break
    with pytest.raises(DeadlineExceededError, match="chunk boundary"):
        h.result(timeout=1)
    assert 0 < len(h.tokens) < 40      # partial progress, then the axe
    assert len(eng.free_slots()) == 1  # the slot came back
    faults.reset()
    nxt = sched.submit(_prompt(4, 1), SamplingParams(max_new_tokens=3))
    _drain(sched, [nxt])
    assert len(nxt.result(timeout=1)) == 3


def test_deadline_caps_queue_full_wait(setup):
    """The end-to-end bound includes backpressure: a deadlined submit
    against a full queue must fail typed within ~deadline_s, not sit out
    the full queue-wait timeout and then enqueue with a fresh clock."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=1)
    sched = Scheduler(eng, max_queue=1)
    sched.submit(_prompt(4, 0), SamplingParams(max_new_tokens=4))
    t0 = time.perf_counter()
    with pytest.raises(QueueFullError):
        sched.submit(_prompt(4, 1), SamplingParams(max_new_tokens=4),
                     timeout=30.0, deadline_s=0.2)
    assert time.perf_counter() - t0 < 2.0


def test_deadline_validation(setup):
    cfg, model, params = setup
    sched = Scheduler(InferenceEngine(params, cfg, num_slots=1))
    with pytest.raises(ValueError, match="deadline_s"):
        sched.submit(_prompt(4, 0), SamplingParams(max_new_tokens=2),
                     deadline_s=0.0)


# -- admission control (the deadline oracle) ------------------------------


def test_admission_rejects_infeasible_deadline(setup, tmp_path):
    """The acceptance oracle: once the tokens/s EWMA is live, a request
    with an impossible deadline is rejected at submit — typed, with a
    retry hint, NEVER enqueued — while a feasible request submitted
    concurrently completes."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2)
    metrics = ServeMetrics(str(tmp_path))
    sched = Scheduler(eng, max_queue=8, metrics=metrics)
    # prime the EWMA the way production does: a driver loop ticking
    # metrics while real requests decode
    warm = [sched.submit(_prompt(5, i), SamplingParams(
        max_new_tokens=8, seed=i)) for i in range(2)]
    while any(h.status in (RequestStatus.QUEUED, RequestStatus.RUNNING)
              for h in warm):
        sched.step()
        metrics.engine_tick(eng.stats, queue_depth=sched.queue_depth())
    assert metrics.tokens_per_s_ewma() is not None
    depth_before = sched.queue_depth()
    with pytest.raises(AdmissionRejectedError, match="shed at admission") \
            as exc_info:
        sched.submit(_prompt(5, 7), SamplingParams(max_new_tokens=40),
                     deadline_s=1e-4)
    assert exc_info.value.retry_after_s > 0
    assert sched.queue_depth() == depth_before          # never enqueued
    assert metrics.headline()["requests_rejected"] == 1
    # a feasible request submitted right after the reject still completes
    ok = sched.submit(_prompt(5, 8), SamplingParams(max_new_tokens=6,
                                                    seed=8),
                      deadline_s=120.0)
    _drain(sched, [ok])
    assert len(ok.result(timeout=1)) == 6


# -- NaN quarantine (the quarantine oracle) -------------------------------


def test_nan_quarantine_isolates_slot(setup, tmp_path):
    """An injected NaN in one slot's KV cache fails ONLY that request
    (typed); the neighbor slot's tokens are IDENTICAL to an uncontended
    run, and the quarantined counter ticks."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2)
    metrics = ServeMetrics(str(tmp_path))
    sched = Scheduler(eng, max_queue=4, metrics=metrics)
    pa, pb = _prompt(6, 20), _prompt(7, 21)
    ref_b = generate_fast(params, cfg, pb[None], 12, temperature=0.9,
                          top_k=7, seed=11)[0, 7:].tolist()
    ha = sched.submit(pa, SamplingParams(max_new_tokens=12,
                                         temperature=0.9, top_k=7,
                                         seed=10))
    hb = sched.submit(pb, SamplingParams(max_new_tokens=12,
                                         temperature=0.9, top_k=7,
                                         seed=11))
    sched.step()                       # both admitted + one decode step
    assert ha.status is RequestStatus.RUNNING
    slot_a = next(s for s, r in sched._by_slot.items() if r is ha)
    # poison slot A's float cache rows (K/V) — the engine-visible shape
    # of a numerical fault confined to one row
    eng._cache = jax.tree.map(
        lambda x: x.at[slot_a].set(jnp.nan)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, eng._cache)
    _drain(sched, [ha, hb])
    with pytest.raises(SlotQuarantinedError, match="quarantined"):
        ha.result(timeout=1)
    assert hb.result(timeout=1) == ref_b       # neighbor untouched
    assert eng.stats.quarantined == 1
    head = metrics.headline()
    assert head["requests_quarantined"] == 1
    assert head["requests_done"] == 1
    # the quarantined slot is free and a fresh admit fully overwrites
    # the poisoned rows — the slot serves cleanly again
    hc = sched.submit(pb, SamplingParams(max_new_tokens=12,
                                         temperature=0.9, top_k=7,
                                         seed=11))
    _drain(sched, [hc])
    assert hc.result(timeout=1) == ref_b


def test_nan_quarantine_catches_slot_finishing_mid_chunk(setup):
    """Regression: with decode_chunk > 1, a poisoned slot that hits
    max-tokens MID-chunk goes inactive before the chunk tail — the
    quarantine check must still catch it (its final-step logits flow
    from the NaN cache rows), not deliver the garbage as a completed
    request."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2, decode_chunk=4)
    # max_new=3: one token from prefill, two from the next chunk — the
    # slot deactivates at scanned step 2 of 4, well before the tail
    slot, ev = eng.admit(_prompt(6, 30), SamplingParams(max_new_tokens=3,
                                                        seed=12))
    assert not ev.finished
    eng._cache = jax.tree.map(
        lambda x: x.at[slot].set(jnp.nan)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, eng._cache)
    events = [e for e in eng.step() if e.slot == slot]
    assert events and all(e.poisoned for e in events)
    assert eng.stats.quarantined == 1
    assert slot in eng.free_slots()


# -- supervisor -----------------------------------------------------------


def _make_supervised(params, cfg, num_slots=2, metrics=None, **sup_kw):
    def factory():
        return InferenceEngine(params, cfg, num_slots=num_slots)
    sched = Scheduler(factory(), max_queue=16, metrics=metrics)
    sup = Supervisor(sched, factory, metrics=metrics, log=lambda *a, **k:
                     None, **sup_kw)
    return sched, sup


def test_supervisor_recovers_engine_exception(setup):
    """serve.decode raises at dispatch 2: the in-flight request fails
    typed, the engine is rebuilt, the next request completes — and the
    rebuild is WARM: same config, same device-program registry, so the
    failover rebuild + recovery request trigger ZERO new program builds
    (the supervisor-failover zero-recompile seam, ISSUE 9)."""
    from gym_tpu.programs import compile_counter

    cfg, model, params = setup
    sched, sup = _make_supervised(params, cfg, dispatch_timeout_s=30.0,
                                  max_restarts=3)
    faults.install("serve.decode", "oserror", first=2, last=2)
    sup.start()
    try:
        h = sched.submit(_prompt(5, 0), SamplingParams(max_new_tokens=8,
                                                       seed=3))
        with pytest.raises(EngineFailedError, match="InjectedFault"):
            h.result(timeout=60)
        # the failed request built everything this config/bucket needs;
        # everything from here — the supervisor's engine rebuild and the
        # recovery request — must be served by the shared registry
        builds0 = compile_counter()
        assert sup.restarts == 1
        ref = generate_fast(params, cfg, _prompt(5, 1)[None], 6,
                            temperature=0.8, top_k=5, seed=4)
        h2 = sched.submit(_prompt(5, 1), SamplingParams(
            max_new_tokens=6, temperature=0.8, top_k=5, seed=4))
        assert h2.result(timeout=60) == ref[0, 5:].tolist()
        assert sup.failed is None
        assert compile_counter() == builds0   # zero-recompile failover
    finally:
        sup.stop(join_timeout_s=10)


def test_supervisor_recovers_wedged_dispatch(setup):
    """serve.decode hangs at dispatch 2: the watchdog reaps the wedged
    driver, in-flight fails typed WITHIN the watchdog deadline, the
    rebuilt engine serves exact tokens — and when the abandoned thread
    finally wakes, the scheduler epoch discards it (post-wake requests
    still match generate_fast: no cross-generation corruption)."""
    cfg, model, params = setup
    sched, sup = _make_supervised(params, cfg, dispatch_timeout_s=0.4,
                                  max_restarts=3)
    faults.install("serve.decode", "hang", arg=1.5, first=2, last=2)
    sup.start()
    try:
        t0 = time.perf_counter()
        h = sched.submit(_prompt(5, 0), SamplingParams(max_new_tokens=8,
                                                       seed=3))
        with pytest.raises(EngineFailedError, match="wedged"):
            h.result(timeout=60)
        assert time.perf_counter() - t0 < 10.0   # typed failure, fast
        assert sup.restarts == 1
        ref = generate_fast(params, cfg, _prompt(6, 1)[None], 6,
                            temperature=0.8, top_k=5, seed=4)
        h2 = sched.submit(_prompt(6, 1), SamplingParams(
            max_new_tokens=6, temperature=0.8, top_k=5, seed=4))
        assert h2.result(timeout=60) == ref[0, 6:].tolist()
        time.sleep(1.6)              # let the abandoned thread wake up
        h3 = sched.submit(_prompt(6, 1), SamplingParams(
            max_new_tokens=6, temperature=0.8, top_k=5, seed=4))
        assert h3.result(timeout=60) == ref[0, 6:].tolist()
    finally:
        sup.stop(join_timeout_s=10)


def test_supervisor_max_restarts_declares_dead(setup):
    """A permanently-broken engine must not crash-loop forever: past
    max_restarts the supervisor fails queued requests typed and stops;
    submit turns into a typed refusal. The server process survives."""
    cfg, model, params = setup
    sched, sup = _make_supervised(params, cfg, dispatch_timeout_s=30.0,
                                  max_restarts=1)
    faults.install("serve.decode", "oserror")          # every dispatch
    sup.start()
    try:
        h = sched.submit(_prompt(5, 0), SamplingParams(max_new_tokens=8))
        with pytest.raises(EngineFailedError):
            h.result(timeout=60)
        assert sup.restarts == 1
        # the rebuilt engine is just as broken: the next request's first
        # dispatch faults again, which exceeds max_restarts
        h2 = sched.submit(_prompt(5, 1), SamplingParams(max_new_tokens=8))
        with pytest.raises(EngineFailedError):
            h2.result(timeout=60)
        deadline = time.perf_counter() + 30.0
        while sup.failed is None and time.perf_counter() < deadline:
            time.sleep(0.05)
        assert sup.failed is not None
        assert sup.restarts == 2                       # 1 allowed + fatal
        with pytest.raises(SchedulerClosedError):
            sched.submit(_prompt(5, 1), SamplingParams(max_new_tokens=2))
    finally:
        sup.stop(join_timeout_s=10)


def test_failover_fails_request_wedged_in_admission(setup):
    """A request popped from the queue but wedged INSIDE engine.admit is
    in neither _queue nor _by_slot — failover must still resolve its
    future typed instead of leaving the client to its wall-clock
    timeout."""
    cfg, model, params = setup
    sched, sup = _make_supervised(params, cfg, dispatch_timeout_s=0.4,
                                  max_restarts=3)
    faults.install("serve.prefill", "hang", arg=1.5, first=1, last=1)
    sup.start()
    try:
        t0 = time.perf_counter()
        h = sched.submit(_prompt(5, 0), SamplingParams(max_new_tokens=6,
                                                       seed=3))
        with pytest.raises(EngineFailedError, match="wedged"):
            h.result(timeout=60)
        assert time.perf_counter() - t0 < 10.0
        # the rebuilt engine serves; the abandoned thread, when it wakes
        # from the hung prefill, must not resurrect the failed request
        h2 = sched.submit(_prompt(5, 1), SamplingParams(max_new_tokens=4,
                                                        seed=4))
        assert len(h2.result(timeout=60)) == 4
        time.sleep(1.6)              # let the abandoned thread wake
        assert h.status is RequestStatus.FAILED
        h3 = sched.submit(_prompt(5, 2), SamplingParams(max_new_tokens=4,
                                                        seed=5))
        assert len(h3.result(timeout=60)) == 4
    finally:
        sup.stop(join_timeout_s=10)


def test_supervisor_clean_stop_is_not_a_failure(setup):
    cfg, model, params = setup
    sched, sup = _make_supervised(params, cfg, dispatch_timeout_s=30.0)
    sup.start()
    h = sched.submit(_prompt(5, 0), SamplingParams(max_new_tokens=5,
                                                   seed=2))
    assert len(h.result(timeout=60)) == 5
    assert sup.stop(join_timeout_s=10)
    assert sup.restarts == 0 and sup.failed is None


# -- metrics: percentiles + synthetic CSV (satellite) ---------------------


def _fake_req(rid, tokens, ttft, lat, exc=None):
    return types.SimpleNamespace(
        id=rid, prompt=np.zeros(4, np.int32), tokens=list(range(tokens)),
        error=None if exc is None else str(exc), exception=exc,
        ttft_s=ttft, avg_token_latency_s=lat)


def test_metrics_percentiles_in_headline(tmp_path):
    m = ServeMetrics(str(tmp_path))
    for i in range(1, 101):          # ttft 0.01..1.00, lat 0.001..0.100
        m.request_done(_fake_req(i, 4, i / 100.0, i / 1000.0),
                       queue_depth=0, active_slots=1)
    head = m.headline()
    assert head["requests_done"] == 100
    # np.percentile linear interpolation over 0.01..1.00
    assert head["ttft_p50_s"] == 0.505
    assert head["ttft_p95_s"] == 0.9505
    assert head["ttft_p99_s"] == 0.9901
    assert head["token_lat_p50_s"] == 0.0505
    assert head["token_lat_p95_s"] == 0.09505
    assert head["token_lat_p99_s"] == 0.09901
    m.close()


def test_metrics_ewma_and_status_rows(tmp_path):
    m = ServeMetrics(str(tmp_path), engine_log_every=1)
    stats = types.SimpleNamespace(tokens_generated=0, active_slots=1)
    m.engine_tick(stats, queue_depth=0)
    time.sleep(0.02)
    stats.tokens_generated = 100
    m.engine_tick(stats, queue_depth=0)
    ewma = m.tokens_per_s_ewma()
    assert ewma is not None and ewma > 0
    # an engine rebuild resets the token counter; the EWMA must survive
    m.engine_restarted()
    stats.tokens_generated = 3
    m.engine_tick(stats, queue_depth=0)
    assert m.tokens_per_s_ewma() == ewma
    # typed failures land typed in the CSV
    m.request_done(_fake_req(1, 2, 0.1, 0.01,
                             exc=DeadlineExceededError("late")),
                   queue_depth=0, active_slots=1)
    m.request_done(_fake_req(2, 2, 0.1, 0.01,
                             exc=SlotQuarantinedError("nan")),
                   queue_depth=0, active_slots=1)
    m.request_rejected(queue_depth=0, active_slots=1)
    head = m.headline()
    assert head["requests_shed"] == 1
    assert head["requests_quarantined"] == 1
    assert head["requests_rejected"] == 1
    assert head["engine_restarts"] == 1
    m.close()


def test_metrics_ewma_resets_after_idle(tmp_path):
    """A stale-low EWMA must not reject deadline'd requests forever: a
    fully idle engine (no slots, no queue, no flow) past the reset
    window goes COLD (EWMA None → optimistic admission). A busy-but-
    stalled engine keeps its honest low rate."""
    m = ServeMetrics(str(tmp_path), ewma_idle_reset_s=0.05)
    stats = types.SimpleNamespace(tokens_generated=0, active_slots=1)
    m.engine_tick(stats, queue_depth=0)
    time.sleep(0.01)
    stats.tokens_generated = 5           # a slow burst: low rate
    m.engine_tick(stats, queue_depth=0)
    assert m.tokens_per_s_ewma() is not None
    # busy-but-stalled: rate survives (the low estimate is the truth)
    stats.active_slots = 1
    time.sleep(0.06)
    m.engine_tick(stats, queue_depth=0)
    time.sleep(0.06)
    m.engine_tick(stats, queue_depth=0)
    assert m.tokens_per_s_ewma() is not None
    # fully idle past the window: cold again
    stats.active_slots = 0
    m.engine_tick(stats, queue_depth=0)
    time.sleep(0.06)
    m.engine_tick(stats, queue_depth=0)
    assert m.tokens_per_s_ewma() is None
    m.close()


def test_read_headline_synthetic_csv(tmp_path):
    """read_headline recomputes the live headline from serve.csv alone —
    pinned on a synthetic file with known percentiles and counts."""
    path = tmp_path / "serve.csv"
    rows = ["ts_s,kind,request_id,status,queue_depth,active_slots,"
            "prompt_tokens,new_tokens,ttft_s,avg_token_latency_s,"
            "cum_tokens,tokens_per_s"]
    for i in range(1, 101):
        rows.append(f"{i / 10.0:.4f},request,{i},done,0,1,4,3,"
                    f"{i / 100.0:.5f},{i / 1000.0:.5f},{3 * i},1.0")
    rows.append("10.2,request,101,shed,0,1,4,1,0.5,,301,1.0")
    rows.append("10.3,request,102,quarantined,1,1,4,2,0.5,0.1,303,1.0")
    rows.append("10.4,request,,rejected,1,1,,,,,303,1.0")
    rows.append("10.5,engine,,restart,,,,,,,303,1.0")
    rows.append("10.6,engine,,,0,0,,,,,303,1.0")
    path.write_text("\n".join(rows) + "\n")
    head = read_headline(str(path))
    assert head["requests_done"] == 100
    assert head["requests_failed"] == 2
    assert head["requests_shed"] == 1
    assert head["requests_quarantined"] == 1
    assert head["requests_rejected"] == 1
    assert head["engine_restarts"] == 1
    assert head["tokens_out"] == 303
    assert head["wall_s"] == 10.6
    # percentiles over the 100 done + 2 failed ttfts (102 samples)
    assert head["ttft_p99_s"] == pytest.approx(0.9899, abs=1e-4)
    assert head["mean_token_latency_s"] is not None


# -- HTTP entry point -----------------------------------------------------


@pytest.fixture()
def http_server(setup, tmp_path):
    cfg, model, params = setup
    from gym_tpu.serve.__main__ import create_server
    handle = create_server(params, cfg, port=0, num_slots=2,
                           metrics_dir=str(tmp_path),
                           dispatch_timeout=30.0, request_timeout=120.0)
    t = threading.Thread(target=handle.httpd.serve_forever, daemon=True)
    t.start()
    yield handle
    handle.close()
    t.join(timeout=10)


def _post(port, body_bytes, headers=None, path="/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", body_bytes,
        {"Content-Type": "application/json", **(headers or {})})
    try:
        r = urllib.request.urlopen(req, timeout=120)
        return r.status, json.loads(r.read()), r.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def test_http_malformed_json_is_400(http_server):
    code, body, _ = _post(http_server.port, b"{not json")
    assert code == 400
    assert "malformed JSON" in body["error"]
    code, body, _ = _post(http_server.port, b"[1, 2, 3]")
    assert code == 400
    assert "must be an object" in body["error"]


def test_http_oversized_prompt_is_400_typed(http_server):
    payload = json.dumps({"prompt": list(range(40)),
                          "max_new_tokens": 40}).encode()
    code, body, _ = _post(http_server.port, payload)
    assert code == 400
    assert "exceeds the KV cache" in body["error"]
    code, body, _ = _post(http_server.port, json.dumps(
        {"prompt": [1, 2, 999]}).encode())
    assert code == 400
    assert "token ids" in body["error"]


def test_http_roundtrip_and_deadline_reject(http_server):
    """Happy path primes the EWMA; an infeasible deadline (body field or
    X-Deadline-S header) then draws 429 + Retry-After; a feasible request
    still completes — load shedding under deadline pressure."""
    ok = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 6,
                     "top_k": 4, "seed": 0}).encode()
    for _ in range(2):
        code, body, _ = _post(http_server.port, ok)
        assert code == 200 and len(body["tokens"]) == 6
    infeasible = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 40,
                             "deadline_s": 1e-4}).encode()
    code, body, headers = _post(http_server.port, infeasible)
    assert code == 429
    assert "shed at admission" in body["error"]
    assert int(headers["Retry-After"]) >= 1
    # header spelling of the same deadline
    code, body, headers = _post(
        http_server.port,
        json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 40}).encode(),
        headers={"X-Deadline-S": "0.0001"})
    assert code == 429 and headers["Retry-After"] is not None
    code, body, _ = _post(http_server.port, ok)
    assert code == 200 and len(body["tokens"]) == 6
    stats = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{http_server.port}/stats", timeout=30).read())
    assert stats["requests_rejected"] == 2
    assert stats["engine_restarts"] == 0
    assert stats["ttft_p50_s"] is not None


def test_http_fault_site_is_503_not_traceback(http_server):
    faults.install("serve.http", "oserror", first=1, last=1)
    code, body, headers = _post(http_server.port, json.dumps(
        {"prompt": [1, 2, 3], "max_new_tokens": 2}).encode())
    assert code == 503
    assert "InjectedFault" in body["error"]
    assert headers["Retry-After"] is not None


def test_http_engine_wedge_recovery(setup, tmp_path):
    """The chaos drill in-process: a hung decode dispatch fails the
    in-flight request typed (503, within its deadline) while the server
    stays up; the supervisor rebuilds the engine and the next request
    succeeds."""
    cfg, model, params = setup
    from gym_tpu.serve.__main__ import create_server
    handle = create_server(params, cfg, port=0, num_slots=2,
                           metrics_dir=str(tmp_path),
                           dispatch_timeout=0.5, request_timeout=120.0)
    t = threading.Thread(target=handle.httpd.serve_forever, daemon=True)
    t.start()
    try:
        faults.install("serve.decode", "hang", arg=1.5, first=2, last=2)
        t0 = time.perf_counter()
        code, body, _ = _post(handle.port, json.dumps(
            {"prompt": [1, 2, 3], "max_new_tokens": 8,
             "deadline_s": 30.0}).encode())
        elapsed = time.perf_counter() - t0
        assert code == 503                    # engine fault ≠ 500
        assert "EngineFailedError" in body["error"]
        assert elapsed < 30.0                 # inside the deadline
        code, body, _ = _post(handle.port, json.dumps(
            {"prompt": [1, 2, 3], "max_new_tokens": 6,
             "top_k": 4, "seed": 1}).encode())
        assert code == 200 and len(body["tokens"]) == 6
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{handle.port}/stats", timeout=30).read())
        assert stats["engine_restarts"] == 1
        assert stats["status"] == "ok"
        # let the abandoned hung thread wake and self-discard BEFORE the
        # interpreter exits — a daemon thread reaped mid-C-call aborts
        # the process ("terminate called without an active exception")
        time.sleep(max(0.0, 1.6 - (time.perf_counter() - t0)))
    finally:
        handle.close()
        t.join(timeout=10)
