"""The kill harness (ISSUE 2 acceptance): ``fit`` in a subprocess,
``kill -9`` at every registered fault-injection site, resume, and the
stitched loss trajectory must be BIT-IDENTICAL to an uninterrupted run.

Mechanics: the worker (``tests/_kill_worker.py``) runs a deterministic
tiny fit with checkpoints every 3 steps and the crash-resume CSV logger;
``GYM_TPU_FAULTS`` arms a SIGKILL at a chosen site/hit. After the crash
the same command is relaunched fault-free and ``fit(resume="auto")``
picks up from the newest valid checkpoint. The comparison artifact is
``train.csv`` — byte equality against the baseline proves the resumed
trajectory (steps, losses, lr, comm accounting) is exactly the
uninterrupted one.

The SIGTERM drill additionally exercises the preemption path: the
worker must exit 0 (clean, not hung), report ``preempted=True``, and
leave a valid emergency checkpoint a resume can continue from.

Kept subprocess-light: one shared baseline + a persistent XLA compile
cache across relaunches (2-core CPU container budget, ISSUE 2 satellite:
``scripts/ci_faults.sh`` runs this file).
"""

import os
import json
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_kill_worker.py")
MAX_STEPS = 12
CKPT_INTERVAL = 3

# site → (kill hit index, sync checkpointing?). Hits are chosen mid-run
# so at least one checkpoint is durably committed before the crash and
# real work remains after it. The two loop-side sites use SYNCHRONOUS
# checkpoints (commits deterministically precede later boundaries; with
# the async writer a warm-cache run reaches boundary 8 before the writer
# commits anything). The two writer-thread sites keep the async path —
# that's where those sites live — and rely on the writer's serialization:
# the hit-1 save commits before the hit-2 attempt dies.
# dispatch.boundary/prefetch.fill hits count per dispatch (12 total);
# checkpoint.write/device_get hits count per save attempt (saves land at
# steps 3, 6, 9, 12).
KILL_SITES = {
    "dispatch.boundary": (8, True),
    "prefetch.fill": (7, True),
    "checkpoint.write": (2, False),
    "checkpoint.device_get": (2, False),
}


@pytest.fixture(scope="session")
def scratch(tmp_path_factory):
    return tmp_path_factory.mktemp("kill_harness")


def _run_worker(save_dir, log_dir, *, faults="", result=None, timeout=240,
                sync_ckpt=False, strategy=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # 2 devices, not the 16 conftest forces in-process: each subprocess
    # pays backend startup, and the workload only needs the node axis
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["GYM_TPU_FAULTS"] = faults
    env["GYM_TPU_IO_RETRIES"] = "2"
    env["GYM_TPU_IO_RETRY_BASE_S"] = "0.01"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, WORKER, "--save-dir", str(save_dir),
           "--log-dir", str(log_dir), "--max-steps", str(MAX_STEPS),
           "--ckpt-interval", str(CKPT_INTERVAL)]
    if result:
        cmd += ["--result", str(result)]
    if sync_ckpt:
        cmd += ["--sync-ckpt"]
    if strategy:
        cmd += ["--strategy", strategy]
    return subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)


def _train_csv(log_dir):
    with open(os.path.join(str(log_dir), "kill", "train.csv")) as f:
        return f.read()


@pytest.fixture(scope="session")
def baseline(scratch):
    """One uninterrupted 0→12 run: the oracle every crash+resume
    trajectory must reproduce byte-for-byte. Also seeds the shared
    compile cache for every later relaunch."""
    os.environ.setdefault("GYM_TPU_TEST_COMPILE_CACHE",
                          str(scratch / "xla_cache"))
    save, log, result = (scratch / "base_ckpt", scratch / "base_logs",
                         scratch / "base.json")
    p = _run_worker(save, log, result=result)
    assert p.returncode == 0, p.stderr[-4000:]
    res = json.loads(open(result).read())
    assert res["steps"] == MAX_STEPS and not res["preempted"]
    return _train_csv(log)


def _kill_resume_roundtrip(scratch, baseline, site):
    hit, sync_ckpt = KILL_SITES[site]
    save = scratch / f"{site}_ckpt"
    log = scratch / f"{site}_logs"
    result = scratch / f"{site}.json"

    p = _run_worker(save, log, faults=f"{site}:kill@{hit}",
                    sync_ckpt=sync_ckpt)
    assert p.returncode == -signal.SIGKILL, (
        f"expected SIGKILL death at {site}@{hit}, got rc={p.returncode}\n"
        f"{p.stderr[-4000:]}")
    assert not os.path.exists(result)

    # fault-free resume (same checkpointing mode as the crashed run)
    p = _run_worker(save, log, result=result, sync_ckpt=sync_ckpt)
    assert p.returncode == 0, p.stderr[-4000:]
    res = json.loads(open(result).read())
    assert res["steps"] == MAX_STEPS
    # the resume genuinely started from a checkpoint, not from scratch
    first_logged = res["losses"][0][0]
    assert first_logged > 0, "resume restarted from step 0"
    assert first_logged % CKPT_INTERVAL == 0
    assert _train_csv(log) == baseline, (
        f"crash at {site}@{hit} + resume is not bit-identical")


def test_kill9_at_dispatch_boundary_resumes_bit_identical(scratch, baseline):
    _kill_resume_roundtrip(scratch, baseline, "dispatch.boundary")


@pytest.mark.slow
@pytest.mark.parametrize("site", ["prefetch.fill", "checkpoint.write",
                                  "checkpoint.device_get"])
def test_kill9_at_site_resumes_bit_identical(scratch, baseline, site):
    _kill_resume_roundtrip(scratch, baseline, site)


def test_kill9_compressed_diloco_residual_roundtrips_bit_identical(
        scratch, baseline):
    """ISSUE 12 satellite: the error-feedback residual is TRAINING STATE
    and must survive ``fit(resume=...)``. The worker runs compressed
    DiLoCo (int4, H=2) with checkpoints every 3 steps, so every
    checkpoint holds a mid-cycle NONZERO residual; kill -9 at a dispatch
    boundary past a durable save, resume fault-free, and the stitched
    ``train.csv`` must be byte-identical to the uninterrupted run — a
    residual that failed to restore (or restored zeroed) would change
    every post-resume outer round's delivered delta and the losses with
    it. (``baseline`` is only depended on for the shared compile
    cache.)"""
    save = scratch / "ef_ckpt"
    log = scratch / "ef_logs"
    result = scratch / "ef.json"

    # uninterrupted oracle for THIS strategy
    p = _run_worker(scratch / "ef_base_ckpt", scratch / "ef_base_logs",
                    result=scratch / "ef_base.json", sync_ckpt=True,
                    strategy="diloco_int4")
    assert p.returncode == 0, p.stderr[-4000:]
    oracle = _train_csv(scratch / "ef_base_logs")

    p = _run_worker(save, log, faults="dispatch.boundary:kill@8",
                    sync_ckpt=True, strategy="diloco_int4")
    assert p.returncode == -signal.SIGKILL, p.stderr[-4000:]

    p = _run_worker(save, log, result=result, sync_ckpt=True,
                    strategy="diloco_int4")
    assert p.returncode == 0, p.stderr[-4000:]
    res = json.loads(open(result).read())
    assert res["steps"] == MAX_STEPS
    first_logged = res["losses"][0][0]
    assert first_logged > 0 and first_logged % CKPT_INTERVAL == 0
    assert _train_csv(log) == oracle, (
        "compressed DiLoCo crash+resume is not bit-identical — the "
        "error-feedback residual did not round-trip")


def test_sigterm_drill_emergency_checkpoint_and_clean_exit(scratch,
                                                           baseline):
    save = scratch / "sigterm_ckpt"
    log = scratch / "sigterm_logs"
    result = scratch / "sigterm.json"

    # deterministic preemption: the fault site SIGTERMs the process at
    # the 5th dispatch boundary; fit must checkpoint and exit 0
    p = _run_worker(save, log, result=result,
                    faults="dispatch.boundary:sigterm@5")
    assert p.returncode == 0, (
        f"SIGTERM drill did not exit cleanly: rc={p.returncode}\n"
        f"{p.stderr[-4000:]}")
    res = json.loads(open(result).read())
    assert res["preempted"] and 0 < res["steps"] < MAX_STEPS

    # the emergency checkpoint is valid: a resume continues from exactly
    # the preempted step and reproduces the uninterrupted trajectory
    p = _run_worker(save, log, result=result)
    assert p.returncode == 0, p.stderr[-4000:]
    res2 = json.loads(open(result).read())
    assert not res2["preempted"] and res2["steps"] == MAX_STEPS
    assert res2["losses"][0][0] == res["steps"]
    assert _train_csv(log) == baseline
