"""Worker for the real two-process multi-host test.

Launched twice by ``tests/test_multiprocess.py`` (process_id 0 and 1).
Each process joins the collective world via
``gym_tpu.parallel.multihost.initialize``, contributes its single CPU
device to a 2-device global mesh, loads ONLY its own node's data
(``multihost.global_batch``), and runs the same jitted DiLoCo training
step — XLA collectives cross the process boundary (the DCN-analog path
the reference covers with its TCP process group,
``exogym/trainer.py:316-347``).

Prints one JSON line: {"pid": ..., "losses": [per-step local-node loss]}.
"""

import json
import sys


def main() -> None:
    port, pid = sys.argv[1], int(sys.argv[2])

    import jax

    # sitecustomize forces jax_platforms='axon,cpu' over the env var, and
    # ANY backend touch (even jax.devices("cpu")) initializes the whole
    # platform list — hanging forever if the axon tunnel is down. Pin the
    # multi-process CPU world as the only platform (the pod analog).
    jax.config.update("jax_platforms", "cpu")

    from gym_tpu.parallel import multihost

    assert multihost.initialize(
        coordinator_address=f"localhost:{port}", num_processes=2,
        process_id=pid,
    )

    import numpy as np

    from gym_tpu.models.base import LossModel
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.parallel.mesh import NodeRuntime
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.train_node import make_init_fn, make_train_step

    devs = jax.devices("cpu")
    assert len(devs) == 2 and jax.process_count("cpu") == 2, (
        f"expected a 2-process world, got {len(devs)} devices"
    )

    num_nodes = 2
    runtime = NodeRuntime.create(num_nodes, devs)
    cfg = GPTConfig(block_size=8, vocab_size=32, n_layer=1, n_head=2,
                    n_embd=16, dropout=0.0, bias=True)
    loss_model = LossModel(GPT(cfg))
    strategy = DiLoCoStrategy(OptimSpec("adamw", lr=1e-3), H=1)
    strategy.finalize(max_steps=3)

    # every process generates the same global stream deterministically,
    # then keeps only its own node's slice — per-host data loading
    rng = np.random.default_rng(7)
    all_batches = rng.integers(
        0, cfg.vocab_size, (3, num_nodes, 1, 2, cfg.block_size),
        dtype=np.int64,
    )
    example = (all_batches[0, 0, 0], all_batches[0, 0, 0])

    init_fn = make_init_fn(loss_model, strategy, example, seed=0)
    state = runtime.init_state(init_fn)
    step = runtime.compile(make_train_step(loss_model, strategy, runtime.ctx))

    losses = []
    for t in range(3):
        mine = all_batches[t, pid:pid + 1]  # this process's node only
        batch = multihost.global_batch(runtime, (mine, np.roll(mine, -1, -1)))
        state, metrics = step(state, batch)
        local_loss = multihost.local_values(metrics["loss"])
        losses.append(round(float(local_loss[0]), 6))

    print(json.dumps({"pid": pid, "losses": losses}), flush=True)


if __name__ == "__main__":
    main()
