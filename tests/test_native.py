"""Native C++ window-gather: parity with the numpy path and availability."""

import numpy as np
import pytest

from gym_tpu.native import gather_windows, native_available


@pytest.mark.parametrize("dtype", [np.uint16, np.int32, np.uint8])
def test_native_matches_numpy(dtype):
    rng = np.random.default_rng(0)
    src = rng.integers(0, 200, size=10_000).astype(dtype)
    idx = rng.integers(0, len(src) - 129, size=300)
    x, y = gather_windows(src, idx, 128)
    win = src[idx[:, None] + np.arange(129)]
    np.testing.assert_array_equal(x, win[:, :-1].astype(np.int32))
    np.testing.assert_array_equal(y, win[:, 1:].astype(np.int32))
    assert x.dtype == np.int32 and y.dtype == np.int32


def test_native_builds_here():
    """This environment ships g++ — the native path must actually engage."""
    assert native_available(np.uint16)


def test_out_of_range_raises_like_numpy():
    """The C++ kernel must not silently read out-of-bounds host memory —
    both paths raise IndexError on bad indices (ADVICE r1)."""
    src = np.arange(100, dtype=np.uint16)
    with pytest.raises(IndexError):
        gather_windows(src, np.array([95]), 8)  # 95+9 > 100
    with pytest.raises(IndexError):
        gather_windows(src, np.array([-1]), 8)
    x, y = gather_windows(src, np.array([91]), 8)  # 91+9 = 100: max legal
    np.testing.assert_array_equal(x[0], np.arange(91, 99))


def test_contiguous_dataset_uses_gather():
    from gym_tpu.data import ContiguousGPTTrainDataset

    src = np.arange(1000, dtype=np.uint16)
    ds = ContiguousGPTTrainDataset(src, block_size=8)
    x, y = ds.take(np.array([0, 5]))
    np.testing.assert_array_equal(x[0], np.arange(8))
    np.testing.assert_array_equal(y[0], np.arange(1, 9))
    np.testing.assert_array_equal(x[1], np.arange(5, 13))
