"""Logger observability: the wandb mirror actually logs when the dep is
live, and degrades LOUDLY when it is not (VERDICT r3 missing #3 — the
path existed but was never exercised; a misconfigured project used to die
silently).

The environment has no wandb (and no egress), so a fake module is
injected into ``sys.modules``: the real test surface is that
``Trainer.fit(wandb_project=...)`` wires every stream (train loss + ppl +
comm bytes, val losses, summary, finish) through whatever ``wandb.init``
returned.
"""

import sys
import types

import numpy as np
import pytest

from gym_tpu import Trainer
from gym_tpu.strategy import OptimSpec, SimpleReduceStrategy

from test_trainer_e2e import TinyLossModel, blobs


class _FakeRun:
    def __init__(self):
        self.logged = []
        self.summary_updates = {}
        self.finished = False
        self.summary = self

    def log(self, metrics, step=None):
        self.logged.append((step, dict(metrics)))

    def update(self, d):
        self.summary_updates.update(d)

    def finish(self):
        self.finished = True


def _install_fake_wandb(monkeypatch, init=None):
    fake = types.ModuleType("wandb")
    run = _FakeRun()

    def default_init(project=None, name=None, config=None):
        fake.init_calls.append(
            {"project": project, "name": name, "config": config})
        return run

    fake.init_calls = []
    fake.init = init or default_init
    monkeypatch.setitem(sys.modules, "wandb", fake)
    return fake, run


def _fit(**kw):
    return Trainer(TinyLossModel(), blobs(128), blobs(32)).fit(
        strategy=SimpleReduceStrategy(OptimSpec("sgd", lr=0.05)),
        num_nodes=2, max_steps=4, batch_size=16, minibatch_size=16,
        val_size=16, val_interval=2, show_progress=False,
        log_dir="/tmp/gym_tpu_test_logs", **kw,
    )


def test_wandb_logger_logs_all_streams(monkeypatch):
    fake, run = _install_fake_wandb(monkeypatch)
    res = _fit(wandb_project="gym-tpu-test", run_name="wb")

    assert np.isfinite(res.final_train_loss)
    assert fake.init_calls == [{
        "project": "gym-tpu-test", "name": "wb",
        "config": fake.init_calls[0]["config"]}]
    cfg = fake.init_calls[0]["config"]
    assert cfg["strategy"] == "SimpleReduceStrategy"
    assert cfg["num_nodes"] == 2

    keys = set()
    for _, metrics in run.logged:
        keys.update(metrics)
    # train stream (loss, ppl, lr, comm) and the local/global val streams
    assert {"train/loss", "train/perplexity", "lr",
            "comm/bytes_step", "comm/bytes_cum"} <= keys
    assert {"local/loss", "global/loss"} <= keys
    # per-step train logging actually fired once per step
    train_steps = [s for s, m in run.logged if "train/loss" in m]
    assert len(train_steps) == 4
    assert "final_train_loss" in run.summary_updates
    assert run.finished


def test_wandb_misconfigured_warns_and_degrades(monkeypatch):
    def bad_init(project=None, name=None, config=None):
        raise RuntimeError("api_key not configured")

    _install_fake_wandb(monkeypatch, init=bad_init)
    with pytest.warns(UserWarning, match="wandb logging disabled"):
        res = _fit(wandb_project="nope")
    assert np.isfinite(res.final_train_loss)


def test_wandb_real_library_offline_smoke(monkeypatch, tmp_path):
    """VERDICT r4 weak #5: the real wandb library (not the fake above) in
    ``mode=offline`` — no network — through a tiny fit. Skips where wandb
    isn't installed (this image); runs wherever the optional dep
    ``gym-tpu[wandb]`` is present. Asserts an offline run directory with a
    logged-data store was produced and the run was finished."""
    wandb = pytest.importorskip("wandb")
    monkeypatch.setenv("WANDB_MODE", "offline")
    monkeypatch.setenv("WANDB_DIR", str(tmp_path))
    monkeypatch.setenv("WANDB_SILENT", "true")
    res = _fit(wandb_project="gym-tpu-offline-smoke", run_name="smoke")
    assert np.isfinite(res.final_train_loss)
    offline_runs = list(tmp_path.glob("wandb/offline-run-*"))
    assert offline_runs, f"no offline run dir under {tmp_path}/wandb"
    stores = (list(offline_runs[0].glob("*.wandb"))
              + list(offline_runs[0].glob("run-*.wandb")))
    assert stores, f"no .wandb data store in {offline_runs[0]}"
