"""The driver's multi-chip gate must be hermetic.

Round 2's `MULTICHIP` artifact went red because a mid-flight libtpu upgrade
broke the *default* accelerator backend, and the dryrun — a CPU-mesh
correctness check — let eager ops touch that backend.  Round 4's went red
because an accelerator *site hook* on ``PYTHONPATH`` (a ``sitecustomize``
that wraps ``xla_bridge``) made ALL backend initialization block — even
``jax.devices("cpu")`` — which no in-process guard can survive.  These
tests poison the calling process both ways — a backend that *raises* and a
site hook that *hangs* — and assert the gate stays green, because
``dryrun_multichip`` never initializes a backend in the calling process:
it spawns a sanitized child (``PYTHONPATH`` = repo root only,
``JAX_PLATFORMS=cpu``, fresh ``XLA_FLAGS``).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

POISON_SCRIPT = """
import jax
import jax._src.xla_bridge as xb

_orig = xb.get_backend
def poisoned(platform=None):
    if platform is None:
        raise RuntimeError("POISONED: default backend (simulated libtpu mismatch)")
    p = platform if isinstance(platform, str) else getattr(platform, "platform", platform)
    if p != "cpu":
        raise RuntimeError(f"POISONED: non-cpu backend {p!r}")
    return _orig(platform)
xb.get_backend = poisoned

from __graft_entry__ import dryrun_multichip
dryrun_multichip(8)
print("DRYRUN_OK_POISONED")
"""


@pytest.mark.slow
def test_dryrun_multichip_survives_poisoned_default_backend():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the default backend be whatever it is
    # XLA flag parsing is last-wins: append so our count beats inherited ones
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, "-c", POISON_SCRIPT], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, (
        f"dryrun touched the (poisoned) default backend:\n{proc.stderr[-4000:]}"
    )
    assert "DRYRUN_OK_POISONED" in proc.stdout


# Simulates /root/.axon_site's failure mode from round 4: a PYTHONPATH
# sitecustomize whose wrapped backend resolution BLOCKS (the real hook
# blocked for minutes with ~0 CPU when its transport tunnel was down).
# Any jax.devices()/get_backend call in a process that loaded this hook
# hangs; only a process that never loaded it can proceed.
HANG_SITECUSTOMIZE = """
import os
if os.environ.get("GRAFT_POISON_HANG"):
    import time
    import jax._src.xla_bridge as xb
    def _hang(*a, **k):
        time.sleep(3600)
    xb.backends = _hang
    xb._get_backend_uncached = _hang
    xb._discover_pjrt_plugins = _hang
"""

HANG_DRIVER = """
from __graft_entry__ import dryrun_multichip
dryrun_multichip(4)
print("DRYRUN_OK_HANGPOISONED")
"""


@pytest.mark.slow
def test_dryrun_multichip_survives_hanging_site_hook(tmp_path):
    """A site hook that *blocks* backend init must not take down the gate.

    The raising poison above is routable in-process; a hanging one is not —
    this asserts the subprocess-sanitization design: the child's PYTHONPATH
    contains no site hook, so the gate completes while the parent process
    (which DID load the hook) never touches a backend.
    """
    (tmp_path / "sitecustomize.py").write_text(HANG_SITECUSTOMIZE)
    env = dict(os.environ)
    # Poison dir first so ITS sitecustomize wins; repo so __graft_entry__
    # imports. The child must drop both and rebuild PYTHONPATH = repo only.
    env["PYTHONPATH"] = f"{tmp_path}{os.pathsep}{REPO}"
    env["GRAFT_POISON_HANG"] = "1"
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "-c", HANG_DRIVER], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, (
        f"gate died under a hanging site hook:\nstdout:{proc.stdout[-2000:]}"
        f"\nstderr:{proc.stderr[-4000:]}"
    )
    assert "DRYRUN_OK_HANGPOISONED" in proc.stdout
    # the sanitized child really ran the shapes (diagnostic tail exists)
    assert "[dryrun] shape 1" in proc.stdout


@pytest.mark.slow
def test_hanging_poison_actually_hangs(tmp_path):
    """Sanity: the poison sitecustomize really does block jax.devices().

    Without this, the test above could pass vacuously (poison not loading).
    """
    (tmp_path / "sitecustomize.py").write_text(HANG_SITECUSTOMIZE)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(tmp_path)
    env["GRAFT_POISON_HANG"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    with pytest.raises(subprocess.TimeoutExpired):
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices('cpu')"],
            env=env, capture_output=True, text=True, timeout=25,
        )
