"""The driver's multi-chip gate must be hermetic.

Round 2's `MULTICHIP` artifact went red because a mid-flight libtpu upgrade
broke the *default* accelerator backend, and the dryrun — a CPU-mesh
correctness check — let eager ops touch that backend. These tests run
``dryrun_multichip`` in a subprocess with the default backend deliberately
poisoned (every non-CPU ``get_backend`` resolution raises, simulating the
libtpu client/terminal mismatch) and assert the gate stays green.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

POISON_SCRIPT = """
import jax
import jax._src.xla_bridge as xb

_orig = xb.get_backend
def poisoned(platform=None):
    if platform is None:
        raise RuntimeError("POISONED: default backend (simulated libtpu mismatch)")
    p = platform if isinstance(platform, str) else getattr(platform, "platform", platform)
    if p != "cpu":
        raise RuntimeError(f"POISONED: non-cpu backend {p!r}")
    return _orig(platform)
xb.get_backend = poisoned

from __graft_entry__ import dryrun_multichip
dryrun_multichip(8)
print("DRYRUN_OK_POISONED")
"""


@pytest.mark.slow
def test_dryrun_multichip_survives_poisoned_default_backend():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the default backend be whatever it is
    # XLA flag parsing is last-wins: append so our count beats inherited ones
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, "-c", POISON_SCRIPT], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, (
        f"dryrun touched the (poisoned) default backend:\n{proc.stderr[-4000:]}"
    )
    assert "DRYRUN_OK_POISONED" in proc.stdout
