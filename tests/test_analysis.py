"""Static-analysis subsystem (ISSUE 6): jaxpr auditor, static comm-trace
reconciliation, and the host-concurrency lint.

Everything here is host-side tracing/AST work — no device programs are
compiled or executed, so the whole file is non-slow.
"""

import json
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from gym_tpu.analysis import (ProgramSpec, audit_program,
                              audit_shipped_programs, check_all_strategies,
                              check_strategy, program_key, recompile_guard,
                              trace_with_axis_env, walk_jaxpr)
from gym_tpu.analysis.lint import (apply_suppressions, lint_source,
                                   load_suppressions, run_lint)
from gym_tpu.analysis.trace_check import (DEFAULT_TEMPLATE,
                                          extract_step_inventory)
from gym_tpu.strategy import (DiLoCoStrategy, OptimSpec,
                              SimpleReduceStrategy, SPARTAStrategy)
from gym_tpu.strategy.base import CollectiveEvent, tree_bytes

F32 = np.float32


# -- walker: collective extraction + constant folding ----------------------


def test_walker_extracts_collectives_over_abstract_axis():
    def fn(x):
        s = lax.psum(x, "node")
        g = lax.all_gather(x, "node", tiled=False)
        rs = lax.psum_scatter(x, "node", scatter_dimension=0, tiled=True)
        return s, g, rs

    closed = trace_with_axis_env(
        fn, (jax.ShapeDtypeStruct((8,), F32),), {"node": 4})
    rep = walk_jaxpr(closed, node_axes=("node",), axis_sizes={"node": 4})
    sites = rep.data_collectives()
    by_op = {s.op: s for s in sites}
    assert set(by_op) == {"all_reduce", "all_gather", "reduce_scatter"}
    assert by_op["all_reduce"].bytes == 32          # input vector
    assert by_op["all_gather"].bytes == 4 * 32      # assembled output
    assert by_op["reduce_scatter"].bytes == 32      # full input
    assert all(s.group == 4 for s in sites)


def test_walker_resolves_cond_with_foldable_predicate():
    """The H-gate pattern: with a concrete step the predicate folds and
    only the LIVE branch's collectives are counted."""

    def make(step):
        def fn(x):
            do = jnp.logical_and(jnp.asarray(step) % 5 == 0,
                                 jnp.asarray(step) > 0)
            return lax.cond(do, lambda a: lax.psum(a, "node"),
                            lambda a: a, x)
        return fn

    tpl = (jax.ShapeDtypeStruct((16,), F32),)
    on = walk_jaxpr(trace_with_axis_env(make(5), tpl, {"node": 4}),
                    node_axes=("node",), axis_sizes={"node": 4})
    off = walk_jaxpr(trace_with_axis_env(make(3), tpl, {"node": 4}),
                     node_axes=("node",), axis_sizes={"node": 4})
    assert len(on.data_collectives()) == 1
    assert off.data_collectives() == []
    assert on.dynamic_collective_conds == 0


def test_walker_folds_constant_metric_through_cond():
    def fn(x):
        do = jnp.asarray(10) % 5 == 0
        comm = lax.cond(do, lambda: jnp.float32(123.0),
                        lambda: jnp.float32(0.0))
        return comm, lax.psum(x, "node")

    closed = trace_with_axis_env(
        fn, (jax.ShapeDtypeStruct((4,), F32),), {"node": 2})
    rep = walk_jaxpr(closed, node_axes=("node",), axis_sizes={"node": 2})
    assert float(np.asarray(rep.out_values[0])) == 123.0


def test_walker_gather_chain_coalesces_to_final_output():
    """AxisCtx.all_gather over ('node', 'vnode') emits one gather per
    axis; the inventory must price them as ONE logical gather with the
    final assembled bytes (the declared-event convention)."""
    from gym_tpu.analysis.jaxpr_tools import abstract_node_ctx

    ctx = abstract_node_ctx(4, n_virt=2)

    def fn(x):
        return ctx.all_gather(x)

    closed = trace_with_axis_env(
        fn, (jax.ShapeDtypeStruct((10,), F32),),
        dict(zip(ctx.axes, ctx.sizes)))
    rep = walk_jaxpr(closed, node_axes=ctx.axes,
                     axis_sizes=dict(zip(ctx.axes, ctx.sizes)))
    sites = rep.data_collectives()
    assert len(sites) == 1
    assert sites[0].group == 4
    assert sites[0].bytes == 4 * 10 * 4


def test_walker_counts_scan_multiplicity_and_control_plane():
    def fn(x):
        def body(c, _):
            return c + lax.psum(c, "node"), None
        y, _ = lax.scan(body, x, None, length=3)
        tiny = lax.psum(jnp.float32(1.0), "node")   # control-plane scalar
        return y, tiny

    closed = trace_with_axis_env(
        fn, (jax.ShapeDtypeStruct((8,), F32),), {"node": 2})
    rep = walk_jaxpr(closed, node_axes=("node",), axis_sizes={"node": 2})
    data = rep.data_collectives()
    assert len(data) == 1 and data[0].times == 3
    ctrl = [s for s in rep.collectives if s.control_plane]
    assert len(ctrl) == 1 and ctrl[0].bytes == 4


# -- static trace reconciliation (the acceptance oracle) -------------------


@pytest.mark.parametrize("name", [
    "simple_reduce", "zero_reduce", "zero_reduce_vnode", "diloco",
    "fedavg", "sparta", "demo", "sparta_diloco", "noloco", "dynamiq",
    "dynamiq_vnode", "dynamiq_topk", "diloco_int8", "diloco_topk",
    "noloco_int4", "demo_outer"])
def test_static_reconciliation_all_strategies(name):
    """jaxpr-extracted collective inventory == declared comm_events,
    op-for-op and byte-for-byte (folded comm_bytes metric), over a full
    H cycle, for every shipped strategy configuration."""
    res = check_all_strategies(num_nodes=4)[name]
    assert res.ok, res.summary()
    # the cycle actually exercises both silent and communicating steps
    # for the gated strategies
    txs = [s.declared_tx for s in res.steps]
    if name in ("diloco", "fedavg", "noloco", "diloco_int8",
                "diloco_topk", "noloco_int4", "demo_outer"):
        # the cycle exercises both silent and communicating steps
        assert any(t == 0 for t in txs) and any(t > 0 for t in txs)
    if name in ("diloco_int8", "diloco_topk", "noloco_int4",
                "demo_outer"):
        # the compressed outer rounds talk at well under the dense
        # round's cost (int8 ≈ 1/4, int4 ≈ 1/8, top-k 5% ≈ 1/12 of the
        # respective dense convention)
        psize = tree_bytes(DEFAULT_TEMPLATE)
        dense_round = (psize if name.startswith("noloco")
                       else 2 * 3 / 4 * psize)
        assert all(t < 0.5 * dense_round for t in txs if t > 0), \
            (txs, dense_round)
    if name == "sparta_diloco":
        # gossip every step, outer round only at H: two distinct levels
        assert len(set(round(t) for t in txs)) >= 2
    if name.startswith("dynamiq"):
        # compressed ALL-reduce: every step talks, and at well under the
        # dense 2(K−1)/K·|θ| f32 cost (int8 ≈ 1/4, topk 5% ≈ 1/5)
        psize = tree_bytes(DEFAULT_TEMPLATE)
        dense = 2 * 3 / 4 * psize
        assert all(0 < t < 0.5 * dense for t in txs), (txs, dense)


def test_diloco_h_gate_static_cadence():
    """Off-H steps must extract ZERO node collectives (the skip branch),
    and the H step must extract the outer all_reduce."""
    s = DiLoCoStrategy(H=5)
    s.finalize(32)
    rep_off = extract_step_inventory(s, DEFAULT_TEMPLATE, 4, step=3)
    rep_on = extract_step_inventory(s, DEFAULT_TEMPLATE, 4, step=5)
    assert rep_off.data_collectives() == []
    assert float(np.asarray(rep_off.out_values[0])) == 0.0
    ops = {c.op for c in rep_on.data_collectives()}
    assert ops == {"all_reduce"}


def test_sparta_static_tx_is_realized_mask_bytes_not_expectation():
    """The folded static metric must equal the REALIZED shared-PRNG mask
    bytes (varying per step), not the p·|θ| expectation — the exact
    property the runtime test pinned with a real fit, now proven by
    constant folding alone."""
    s = SPARTAStrategy(inner_optim=OptimSpec("sgd", lr=0.0), p_sparta=0.3)
    s.finalize(16)
    psize = tree_bytes(DEFAULT_TEMPLATE)
    seen = set()
    for t in (0, 1, 2):
        rep = extract_step_inventory(s, DEFAULT_TEMPLATE, 4, step=t)
        static = float(np.asarray(rep.out_values[0]))
        declared = sum(e.per_node_tx()
                       for e in s.comm_events(t, DEFAULT_TEMPLATE, 4))
        assert static == pytest.approx(declared, rel=1e-6)
        expectation = 2 * 3 / 4 * 0.3 * psize
        assert static != pytest.approx(expectation, rel=1e-3)
        seen.add(round(static, 3))
    assert len(seen) == 3   # fresh Bernoulli draw per step


def test_falsified_trace_is_caught():
    """A strategy whose declared trace lies — wrong bytes or wrong op —
    must fail the static reconciliation (the ISSUE 6 acceptance
    fixture)."""

    class LyingBytes(SimpleReduceStrategy):
        def comm_events(self, step, params, num_nodes):
            return [CollectiveEvent(
                "all_reduce", float(tree_bytes(params)) / 2, num_nodes)]

    class LyingOp(SimpleReduceStrategy):
        def comm_events(self, step, params, num_nodes):
            return [CollectiveEvent(
                "all_gather", float(tree_bytes(params)), num_nodes)]

    class SilentExtra(SimpleReduceStrategy):
        def comm_events(self, step, params, num_nodes):
            return []      # claims silence while psumming every step

    for cls, frag in ((LyingBytes, "static comm_bytes"),
                      (LyingOp, "ops mismatch"),
                      (SilentExtra, "ops mismatch")):
        res = check_strategy(cls(), num_nodes=4)
        assert not res.ok, cls.__name__
        assert any(frag in e for s in res.failures() for e in s.errors), \
            (cls.__name__, res.failures()[0].errors)


def test_falsified_low_comm_traces_are_caught():
    """The ISSUE 10 falsification fixtures: byte totals alone cannot
    catch these lies, the structural checks must.

    - WrongPartner: a NoLoCo whose trace declares a rotated partner map
      — every derangement moves the same |θ|, so only the folded
      shared-PRNG draw comparison can refute it.
    - NotAPermutation: declared pairs where one node receives twice.
    - WrongCompressedBytes: a DynamiQ declaring half its codec's honest
      wire bytes — caught by the folded comm_bytes metric.
    - UndeclaredResidualGather: a DynamiQ-topk that all_gathers its
      error-feedback residual every step without declaring it; the wire
      accounting still matches, but the moved bytes exceed the declared
      dense-emulation bound.
    """
    from gym_tpu.strategy import DynamiQStrategy, NoLoCoStrategy
    from gym_tpu.strategy.noloco import NoLoCoCommunicator

    class _WrongPartnerComm(NoLoCoCommunicator):
        def comm_events(self, step, params, num_nodes):
            events = super().comm_events(step, params, num_nodes)
            return [
                CollectiveEvent(
                    e.op, e.bytes, e.group, label=e.label,
                    pairs=tuple((i, (j + 1) % num_nodes)
                                for i, j in e.pairs),
                    emulated_bytes=e.emulated_bytes)
                for e in events]

    class WrongPartner(NoLoCoStrategy):
        def __init__(self):
            super().__init__(H=2)
            self.communication_modules[0].__class__ = _WrongPartnerComm

    class _NotPermComm(NoLoCoCommunicator):
        def comm_events(self, step, params, num_nodes):
            events = super().comm_events(step, params, num_nodes)
            return [
                CollectiveEvent(
                    e.op, e.bytes, e.group, label=e.label,
                    pairs=((0, 1),) * num_nodes,
                    emulated_bytes=e.emulated_bytes)
                for e in events]

    class NotAPermutation(NoLoCoStrategy):
        def __init__(self):
            super().__init__(H=2)
            self.communication_modules[0].__class__ = _NotPermComm

    class WrongCompressedBytes(DynamiQStrategy):
        def comm_events(self, step, params, num_nodes):
            return [
                CollectiveEvent(e.op, e.bytes / 2, e.group, label=e.label,
                                emulated_bytes=e.emulated_bytes)
                for e in super().comm_events(step, params, num_nodes)]

    class UndeclaredResidualGather(DynamiQStrategy):
        def __init__(self):
            super().__init__(codec="topk", frac=0.05)

        def step(self, grads, params, state, step, ctx):
            p, s, m = super().step(grads, params, state, step, ctx)
            # smuggle a dense residual exchange into the declared
            # gather hop; fold a value through so it isn't dead code,
            # but keep the comm_bytes metric (the wire lie) unchanged
            leak = ctx.all_gather(s["residual"])
            s = dict(s, residual=s["residual"] + 0.0 * leak.sum())
            return p, s, m

    for cls, frag in (
            (WrongPartner, "folded shared-PRNG draw"),
            (NotAPermutation, "not a permutation"),
            (WrongCompressedBytes, "static comm_bytes"),
            (UndeclaredResidualGather, "dense-emulation bound")):
        res = check_strategy(cls(), num_nodes=4)
        assert not res.ok, cls.__name__
        assert any(frag in e for s in res.failures() for e in s.errors), \
            (cls.__name__, [s.errors for s in res.failures()])


def test_falsified_compressed_outer_loop_traces_are_caught():
    """The ISSUE 12 falsification fixtures — the codec axis must not
    weaken the gates:

    - WrongWireBytes: a compressed DiLoCo declaring half its link's
      honest wire bytes (codec bytes are far below the dense emulation
      anyway, so only the folded comm_bytes metric can refute it).
    - UndeclaredResidualExchange: a compressed NoLoCo that gossips its
      error-feedback residual alongside the params without declaring it
      — wire accounting still matches, but the gathered dense payload
      exceeds the declared ``emulated_bytes`` bound.
    """
    from gym_tpu.strategy import DiLoCoStrategy, NoLoCoStrategy
    from gym_tpu.strategy.noloco import NoLoCoCommunicator

    class WrongWireBytes(DiLoCoStrategy):
        def __init__(self):
            super().__init__(H=2, codec="int4")

        def comm_events(self, step, params, num_nodes):
            return [
                CollectiveEvent(e.op, e.bytes / 2, e.group, label=e.label,
                                emulated_bytes=e.emulated_bytes)
                for e in super().comm_events(step, params, num_nodes)]

    class _LeakyGossip(NoLoCoCommunicator):
        def communicate(self, params, mstate, step, ctx):
            params, mstate, comm = super().communicate(
                params, mstate, step, ctx)
            # smuggle the residual into an extra gather; fold a value
            # through so it isn't dead code, keep the metric unchanged
            leak = ctx.all_gather(mstate["ef_residual"])
            mstate = dict(mstate,
                          ef_residual=mstate["ef_residual"]
                          + 0.0 * leak.sum())
            return params, mstate, comm

    class UndeclaredResidualExchange(NoLoCoStrategy):
        def __init__(self):
            super().__init__(H=2, codec="int4")
            self.communication_modules[0].__class__ = _LeakyGossip

    for cls, frag in (
            (WrongWireBytes, "static comm_bytes"),
            (UndeclaredResidualExchange, "dense-emulation bound")):
        res = check_strategy(cls(), num_nodes=4)
        assert not res.ok, cls.__name__
        assert any(frag in e for s in res.failures() for e in s.errors), \
            (cls.__name__, [s.errors for s in res.failures()])


# -- jaxpr audit: donation / callbacks / keys ------------------------------


def _spec(fn, args, donate=(), name="toy", axis_sizes=None):
    return ProgramSpec(name=name, fn=fn, args=tuple(args),
                       donate_args=tuple(donate), axis_sizes=axis_sizes)


def test_donation_unaliased_detected():
    """Donating a buffer no output can alias (shape mismatch) is the
    silent copy the audit exists to catch; the aliasable twin passes."""
    big = jax.ShapeDtypeStruct((128,), F32)

    def shrinks(x):
        return x[:4]

    def keeps(x):
        return x + 1

    bad = audit_program(_spec(shrinks, [big], donate=(0,)))
    assert [f.kind for f in bad.findings] == ["donation-unaliased"]
    good = audit_program(_spec(keeps, [big], donate=(0,)))
    assert good.ok


def test_donation_unused_detected():
    def ignores(x, y):
        return y * 2

    audit = audit_program(_spec(
        ignores, [jax.ShapeDtypeStruct((8,), F32)] * 2, donate=(0,)))
    kinds = [f.kind for f in audit.findings]
    assert "donation-unused" in kinds
    # the same program WITHOUT donating the dead arg is silent
    assert audit_program(_spec(
        ignores, [jax.ShapeDtypeStruct((8,), F32)] * 2)).ok


def test_host_callback_detected_in_hot_path_only():
    def with_cb(x):
        y = jax.pure_callback(
            lambda a: np.asarray(a) * 2, jax.ShapeDtypeStruct((4,), F32), x)
        return x + y

    def clean(x):
        return x * 2

    tpl = [jax.ShapeDtypeStruct((4,), F32)]
    hot = audit_program(_spec(with_cb, tpl))
    assert [f.kind for f in hot.findings] == ["host-callback"]
    cold = audit_program(dataclasses_replace_hot(_spec(with_cb, tpl)))
    assert cold.ok
    assert audit_program(_spec(clean, tpl)).ok


def dataclasses_replace_hot(spec):
    import dataclasses
    return dataclasses.replace(spec, hot_path=False)


def test_debug_print_counts_as_callback():
    def noisy(x):
        jax.debug.print("x={x}", x=x)
        return x + 1

    audit = audit_program(_spec(noisy, [jax.ShapeDtypeStruct((2,), F32)]))
    assert [f.kind for f in audit.findings] == ["host-callback"]


def test_program_key_stability_and_sensitivity():
    tpl = (jax.ShapeDtypeStruct((8,), F32),)
    _, h1 = program_key("p", {"a": 1}, tpl, (0,))
    _, h2 = program_key("p", {"a": 1}, tpl, (0,))
    assert h1 == h2
    # every key component moves the hash
    assert program_key("p", {"a": 2}, tpl, (0,))[1] != h1
    assert program_key("p", {"a": 1}, tpl, ())[1] != h1
    assert program_key("p", {"a": 1},
                       (jax.ShapeDtypeStruct((8,), np.float64),),
                       (0,))[1] != h1


def test_recompile_guard_flags_donation_near_miss():
    tpl = [jax.ShapeDtypeStruct((8,), F32)]

    def f(x):
        return x + 1

    a = audit_program(_spec(f, tpl, donate=(0,), name="fam[x]"))
    b = audit_program(_spec(f, tpl, donate=(), name="fam[y]"))
    for x in (a, b):
        x.family = "fam"
    guard = recompile_guard([a, b])
    assert guard["near_misses"], guard
    assert not guard["collisions"]


@pytest.mark.slow
def test_shipped_programs_audit_clean():
    """The full shipped-program registry: zero unconsumed donations,
    zero hot-path callbacks, zero f64, stable keys. (~10 s of tracing —
    also run by scripts/ci_analyze.sh via the CLI.)"""
    rep = audit_shipped_programs()
    assert rep["violations"] == 0, rep
    names = {p["name"] for p in rep["programs"]}
    assert len(names) == len(rep["programs"]) >= 26
    assert any(n.startswith("serve.decode") for n in names)
    assert any(n.startswith("serve.prefill") for n in names)
    # ISSUE 7: the paged/speculative serving programs are audited too
    assert any(n.startswith("serve.paged_prefill") for n in names)
    assert any(n.startswith("serve.paged_decode") for n in names)
    assert any(n.startswith("serve.spec_decode") for n in names)
    assert any(n.startswith("serve.cow") for n in names)
    # ISSUE 11: the quantized family is audited too — donation-clean
    # int8 pools/scales, distinct names (dtype tag) and distinct keys
    assert any("w=int8" in n and "kv=int8" in n for n in names)
    assert any(n.startswith("serve.paged_decode[") and "w=int8" in n
               for n in names)
    assert rep["recompile_guard"]["n_keys"] == len(rep["programs"])


# -- lint rules, each pinned on a minimal snippet --------------------------


def _lint(src):
    return lint_source(textwrap.dedent(src))


def test_lint_bare_assert():
    vs = _lint("""
        def f(x):
            assert x > 0, "nope"
    """)
    assert [v.rule for v in vs] == ["GT101"]


def test_lint_lock_across_blocking_call():
    vs = _lint("""
        import threading, time, queue

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def bad(self):
                with self._lock:
                    item = self._q.get(timeout=1)
                    time.sleep(0.1)
                    self.fut.result()
                return item

            def good(self):
                with self._lock:
                    n = len(self.items)
                item = self._q.get(timeout=1)
                return n, item
    """)
    assert [v.rule for v in vs] == ["GT102"] * 3


def test_lint_condition_wait_on_held_lock_is_exempt():
    vs = _lint("""
        import threading

        class W:
            def __init__(self):
                self._work = threading.Condition()
                self._stop = threading.Event()

            def ok(self):
                with self._work:
                    while not self.ready:
                        self._work.wait()

            def bad(self):
                with self._work:
                    self._stop.wait(1.0)
    """)
    assert [v.rule for v in vs] == ["GT102"]
    assert "_stop" in vs[0].msg


def test_lint_fsync_under_lock():
    vs = _lint("""
        import threading, os

        class M:
            def __init__(self):
                self._lock = threading.Lock()

            def sync(self):
                with self._lock:
                    self._f.flush()
                    os.fsync(self._f.fileno())
    """)
    assert [v.rule for v in vs] == ["GT102"]


def test_lint_condition_alias_self_deadlock():
    vs = _lint("""
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._drained = threading.Condition(self._lock)

            def bad(self):
                with self._drained:
                    with self._lock:
                        pass
    """)
    assert [v.rule for v in vs] == ["GT103"]
    assert "same underlying lock" in vs[0].msg


def test_lint_lock_order_cycle():
    vs = _lint("""
        import threading

        class A:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert any(v.rule == "GT103" and "cycle" in v.msg for v in vs)


def test_lint_nested_function_does_not_inherit_lock_region():
    vs = _lint("""
        import threading, time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def ok(self):
                with self._lock:
                    def later():
                        time.sleep(1)   # runs on another stack
                    self.cb = later
    """)
    assert vs == []


def test_lint_untyped_raise_and_wallclock():
    vs = _lint("""
        import time

        def f():
            t0 = time.time()
            raise RuntimeError("boom")
    """)
    assert sorted(v.rule for v in vs) == ["GT104", "GT105"]


def test_lint_str_join_and_dict_get_not_flagged():
    vs = _lint("""
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def ok(self):
                with self._lock:
                    s = ", ".join(self.parts)
                    v = self.cfg.get("key")
                return s, v
    """)
    assert vs == []


def test_suppression_budget_and_ratchet(tmp_path):
    supp = tmp_path / "supp.txt"
    supp.write_text(
        "# comment\n"
        "pkg/a.py:GT101 = 2  # legacy asserts\n"
        "pkg/b.py:GT105 = 3  # over-budgeted\n")
    loaded = load_suppressions(str(supp))
    assert loaded[("pkg/a.py", "GT101")] == (2, "legacy asserts")

    from gym_tpu.analysis.lint import LintViolation
    vs = [LintViolation("pkg/a.py", i, "GT101", "m") for i in (1, 2, 3)]
    vs.append(LintViolation("pkg/b.py", 9, "GT105", "m"))
    unsup, notes = apply_suppressions(vs, loaded)
    assert len(unsup) == 1 and unsup[0].line == 3      # beyond budget
    assert any("pkg/b.py:GT105" in n for n in notes)   # ratchet down

    with pytest.raises(ValueError, match="malformed suppression"):
        supp.write_text("what is this line\n")
        load_suppressions(str(supp))


def test_lint_gate_is_green_on_the_shipped_tree():
    """The ISSUE 6 burn-down pin: the real package has ZERO unsuppressed
    violations — 41 bare asserts became typed exceptions, the RuntimeErrors
    grew classes, and durations use perf_counter."""
    violations = run_lint("gym_tpu")
    unsup, notes = apply_suppressions(violations, load_suppressions())
    assert unsup == [], [v.render() for v in unsup]
    assert notes == [], notes   # budgets must stay ratcheted tight


def test_lock_sites_conformance_pinned():
    """The concurrency-audit satellite: the seven Lock/Condition sites
    (scheduler, supervisor, metrics, checkpoint, resilience ×2, plus the
    scheduler's condition) carry no lock-across-blocking-call or
    lock-order violations. metrics.sync()'s fsync-under-lock was the one
    genuine finding and is fixed — this test is the regression pin."""
    violations = run_lint("gym_tpu")
    conc = [v for v in violations if v.rule in ("GT102", "GT103")]
    assert conc == [], [v.render() for v in conc]


def test_metrics_sync_fsyncs_outside_the_lock(tmp_path, monkeypatch):
    """Behavioral twin of the lint pin: while sync()'s fsync is in
    flight, the metrics lock must be FREE — admission control
    (tokens_per_s_ewma) and request_done must not queue behind a disk
    stall."""
    import os as _os

    from gym_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics(str(tmp_path))
    observed = {}
    real_fsync = _os.fsync

    def probing_fsync(fd):
        observed["lock_free"] = m._lock.acquire(timeout=1.0)
        if observed["lock_free"]:
            m._lock.release()
        return real_fsync(fd)

    monkeypatch.setattr(_os, "fsync", probing_fsync)
    m.sync()
    assert observed == {"lock_free": True}
    m.close()
    m.sync()   # straggler sync after close: dropped, not ValueError


# -- CLI -------------------------------------------------------------------


def test_cli_runs_lint_section_and_writes_json(tmp_path):
    from gym_tpu.analysis.__main__ import main

    out = tmp_path / "analysis.json"
    rc = main(["--only", "lint", "--json", str(out)])
    assert rc == 0
    report = json.loads(out.read_text())
    assert report["violations"] == 0
    assert report["sections"]["lint"]["total"] >= 1   # suppressed GT105
