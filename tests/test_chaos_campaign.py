"""Seeded chaos campaigns (ISSUE 20): random fault mixes over every
compatible train-pipeline site, with the three campaign invariants —
no silent divergence, every failure typed, recovery completes.

Fast tests pin the campaign machinery itself (schedule determinism,
spec grammar round-trip, exit classification, the launch/relaunch loop)
against stubs. The slow test is the real thing: >=5 seeded campaigns
over the subprocess kill-harness worker (``tests/_kill_worker.py`` with
``--sync-ckpt --guard``), each verified by byte-comparing the completed
``train.csv`` against a fault-free oracle and restoring params from the
surviving run directory. ``scripts/ci_sdc.sh`` runs the slow test."""

import json
import os
import subprocess
import sys

import pytest

from gym_tpu.utils import chaos
from gym_tpu.utils.chaos import (ChaosEvent, CampaignResult,
                                 GUARD_SAFE_FIRST_HIT,
                                 TRAIN_SITE_ACTIONS, WATCHDOG_EXIT_CODE,
                                 classify_exit, faults_spec,
                                 run_train_campaign, sample_schedule)
from gym_tpu.utils.resilience import FaultRegistry, Watchdog, faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_kill_worker.py")
MAX_STEPS = 12


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# -- schedule sampling ------------------------------------------------------


def test_sample_schedule_deterministic_and_well_formed():
    for seed in range(20):
        a = sample_schedule(seed)
        b = sample_schedule(seed)
        assert a == b, f"seed {seed} not reproducible"
        assert 1 <= len(a) <= 3
        for ev in a:
            assert ev.action in TRAIN_SITE_ACTIONS[ev.site]
            assert ev.last == ev.first  # single-hit by construction
            assert ev.first >= 1
            if ev.site == "dispatch.state":
                # corruption inside the guard's warmup is undetectable
                # by construction — the sampler must never schedule it
                assert ev.first >= GUARD_SAFE_FIRST_HIT
            if ev.action == "delay":
                assert 0.01 <= ev.arg <= 0.1
            if ev.action == "bitflip":
                assert 1 <= ev.arg <= 4
    # seeds actually vary the schedule
    assert len({faults_spec(sample_schedule(s)) for s in range(20)}) > 5


def test_sampled_specs_parse_into_fault_registry():
    # the whole point of spec(): every sampled schedule must be a valid
    # GYM_TPU_FAULTS string the real registry accepts
    for seed in range(30):
        spec = faults_spec(sample_schedule(seed))
        reg = FaultRegistry()
        reg.configure(spec)
        assert len(reg._rules) == len(sample_schedule(seed))


def test_event_spec_grammar():
    assert ChaosEvent("dispatch.boundary", "kill",
                      first=3, last=3).spec() == "dispatch.boundary:kill@3"
    assert ChaosEvent("prefetch.fill", "delay", arg=0.05, first=2,
                      last=2).spec() == "prefetch.fill:delay=0.05@2"
    assert ChaosEvent("dispatch.state", "bitflip", arg=2.0, first=5,
                      last=5).spec() == "dispatch.state:bitflip=2@5"
    assert ChaosEvent("wire.frame", "bitflip", arg=1.0,
                      first=4).spec() == "wire.frame:bitflip=1@4+"
    assert ChaosEvent("checkpoint.write", "oserror", first=2,
                      last=4).spec() == "checkpoint.write:oserror@2-4"
    two = [ChaosEvent("dispatch.boundary", "kill", first=3, last=3),
           ChaosEvent("checkpoint.bytes", "truncate", first=1, last=1)]
    assert faults_spec(two) == ("dispatch.boundary:kill@3,"
                                "checkpoint.bytes:truncate@1")


# -- exit classification ----------------------------------------------------


def test_classify_exit():
    assert classify_exit(0) == "clean"
    assert classify_exit(-9) == "killed"
    assert classify_exit(137) == "killed"
    assert classify_exit(-15) == "sigterm"
    assert classify_exit(143) == "sigterm"
    assert classify_exit(WATCHDOG_EXIT_CODE) == "watchdog"
    assert classify_exit(1, "Traceback ...\nChecksumMismatchError: x") \
        == "typed:ChecksumMismatchError"
    assert classify_exit(1, "GuardTrippedError: loss spike") \
        == "typed:GuardTrippedError"
    assert classify_exit(1, "SomeRandomError: boom") == "unclassified"
    assert classify_exit(1, "") == "unclassified"


def test_watchdog_exit_code_pinned_to_resilience():
    # chaos duplicates the literal to stay importable without jax; this
    # is the tripwire if resilience ever renumbers
    assert WATCHDOG_EXIT_CODE == Watchdog.EXIT_CODE


# -- campaign loop against stub launches ------------------------------------


def test_campaign_first_launch_armed_rest_fault_free():
    seen = []

    def launch(spec):
        seen.append(spec)
        if len(seen) == 1:
            return {"returncode": -9, "stderr": "", "completed": False}
        return {"returncode": 0, "stderr": "", "completed": True}

    res = run_train_campaign(7, launch)
    assert res.ok
    assert res.attempts == ["killed", "clean"]
    assert seen[0] == faults_spec(sample_schedule(7))
    assert seen[1] == ""


def test_campaign_untyped_death_is_violation():
    def launch(spec):
        return {"returncode": 1, "stderr": "KeyError: 'oops'",
                "completed": False}

    res = run_train_campaign(1, launch, max_launches=4)
    assert not res.ok
    assert res.attempts == ["unclassified"]  # stops at the first escape
    assert any("UNTYPED" in v for v in res.violations)


def test_campaign_typed_deaths_retry_until_budget():
    def launch(spec):
        return {"returncode": 1,
                "stderr": "gym_tpu.utils.resilience.InjectedFault: x",
                "completed": False}

    res = run_train_campaign(2, launch, max_launches=3)
    assert not res.completed
    assert res.attempts == ["typed:InjectedFault"] * 3
    assert any("did not complete" in v for v in res.violations)


def test_campaign_verify_violations_and_exceptions_surface():
    ok_launch = lambda spec: {"returncode": 0, "stderr": "",
                              "completed": True}
    res = run_train_campaign(3, ok_launch,
                             verify=lambda: ["csv diverged"])
    assert res.completed and not res.ok
    assert res.violations == ["csv diverged"]

    def bad_verify():
        raise OSError("cannot read train.csv")

    res = run_train_campaign(3, ok_launch, verify=bad_verify)
    assert any("verify() raised OSError" in v for v in res.violations)


def test_campaign_launch_exception_is_violation_not_crash():
    def launch(spec):
        raise RuntimeError("harness bug")

    res = run_train_campaign(4, launch)
    assert not res.ok
    assert any("launch 0 raised RuntimeError" in v
               for v in res.violations)


# -- the real thing: seeded campaigns over the subprocess worker ------------


def _run_worker(save_dir, log_dir, *, spec="", result=None, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["GYM_TPU_FAULTS"] = spec
    env["GYM_TPU_IO_RETRIES"] = "2"
    env["GYM_TPU_IO_RETRY_BASE_S"] = "0.01"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, WORKER, "--save-dir", str(save_dir),
           "--log-dir", str(log_dir), "--max-steps", str(MAX_STEPS),
           "--ckpt-interval", "3", "--sync-ckpt", "--guard"]
    if result:
        cmd += ["--result", str(result)]
    return subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)


def _train_csv_bytes(log_dir):
    with open(os.path.join(str(log_dir), "kill", "train.csv"), "rb") as f:
        return f.read()


@pytest.fixture(scope="session")
def campaign_scratch(tmp_path_factory):
    return tmp_path_factory.mktemp("chaos")


@pytest.fixture(scope="session")
def campaign_baseline(campaign_scratch):
    """Fault-free oracle run (same worker flags the campaigns use);
    also warms the shared compile cache for every campaign launch."""
    os.environ.setdefault("GYM_TPU_TEST_COMPILE_CACHE",
                          str(campaign_scratch / "xla_cache"))
    result = campaign_scratch / "base.json"
    p = _run_worker(campaign_scratch / "base_ckpt",
                    campaign_scratch / "base_logs", result=result)
    assert p.returncode == 0, p.stderr[-4000:]
    assert json.loads(open(result).read())["steps"] == MAX_STEPS
    return _train_csv_bytes(campaign_scratch / "base_logs")


@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 12, 13, 14, 15])
def test_seeded_campaign_holds_invariants(campaign_scratch,
                                          campaign_baseline, seed):
    from gym_tpu.utils.checkpoint import restore_params

    base = campaign_scratch / f"seed{seed}"
    save, log = base / "ckpt", base / "logs"
    result = base / "result.json"

    def launch(spec):
        if os.path.exists(result):
            os.unlink(result)
        p = _run_worker(save, log, spec=spec, result=result)
        completed = False
        if p.returncode == 0 and os.path.exists(result):
            out = json.loads(open(result).read())
            completed = (out["steps"] == MAX_STEPS
                         and not out["preempted"])
        return {"returncode": p.returncode, "stderr": p.stderr,
                "completed": completed}

    def verify():
        violations = []
        got = _train_csv_bytes(log)
        if got != campaign_baseline:
            violations.append(
                f"seed {seed}: train.csv diverged from fault-free "
                f"oracle ({len(got)} vs {len(campaign_baseline)} bytes)")
        step, params, _extra = restore_params(str(save / "kill"))
        if not params or step <= 0:
            violations.append(
                f"seed {seed}: restore_params failed on surviving run "
                f"dir (step={step})")
        return violations

    res = run_train_campaign(seed, launch, verify=verify)
    assert res.ok, (
        f"campaign seed {seed} violated invariants:\n"
        f"  schedule: {faults_spec(res.events)}\n"
        f"  attempts: {res.attempts}\n"
        f"  violations: {res.violations}")
