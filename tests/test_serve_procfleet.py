"""Out-of-process fleet (ISSUE 13): REAL worker subprocesses behind the
socket-speaking ``ProcessRouter``.

Acceptance oracles pinned here:

- **streaming exact-stream** — a streamed request through a worker
  subprocess concatenates byte-identical to ``generate_fast``.
- **kill -9 splice oracle** — SIGKILL the worker process serving a
  stream after >= 4 tokens reached the client: the router re-dispatches
  with the delivered prefix, the sibling re-derives + suppresses it,
  and the CONCATENATED client stream is byte-identical to an
  uncontended run, inside the original deadline. ``scale_up`` (the
  autoscaler's respawn) restores the fleet and the dead worker leaves
  no zombie.
- **one shared fleet fixture** — workers cost a jax import each; the
  module spawns exactly one 2-worker fleet and the kill test runs LAST
  (ordering matters: ``-p no:randomly``, the repo-wide convention).
"""

import os
import signal
import tempfile
import time

import numpy as np
import pytest

import jax

from gym_tpu.models.nanogpt import GPT, GPTConfig, generate_fast
from gym_tpu.serve.engine import SamplingParams
from gym_tpu.serve.metrics import ServeMetrics
from gym_tpu.serve.router import build_process_fleet


@pytest.fixture(scope="module")
def fleet():
    cfg = GPTConfig(block_size=64, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True)
    model = GPT(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int64),
                        train=False)["params"]
    metrics = ServeMetrics(tempfile.mkdtemp(prefix="gym_tpu_pfm_"))
    router = build_process_fleet(
        params, cfg, tempfile.mkdtemp(prefix="gym_tpu_pf_"),
        replicas=2, num_slots=2, metrics=metrics, no_warmup=True,
        max_restarts=0, log=lambda *a, **k: None)
    router.start()
    router.wait_ready(timeout_s=240)
    yield cfg, params, router, metrics
    assert router.close(drain_deadline_s=60) is True
    metrics.close()
    # no zombies: every spawned worker pid is gone (or reaped)
    for rep in router.replicas:
        if rep.proc is not None:
            assert rep.proc.poll() is not None, \
                f"worker {rep.id} (pid {rep.pid}) still running"


def _ref(params, cfg, prompt, n, **kw):
    return generate_fast(params, cfg, np.asarray(prompt)[None], n,
                         **kw)[0, len(prompt):].tolist()


def test_proc_stream_exact_and_result_surface(fleet):
    cfg, params, router, _m = fleet
    prompt = [1, 2, 3, 4, 5, 6]
    ref = _ref(params, cfg, prompt, 16, temperature=0.9, top_k=7,
               seed=3)
    pr = router.submit(prompt, SamplingParams(
        max_new_tokens=16, temperature=0.9, top_k=7, seed=3))
    got, chunks = [], 0
    for chunk in pr.stream(timeout=120):
        got.extend(chunk)
        chunks += 1
    assert got == ref
    assert chunks > 1
    assert pr.tokens == ref
    assert pr.ttft_s is not None and pr.ttft_s > 0
    assert pr.done_t is not None
    # buffered surface too (a second request; results are one-shot)
    pr2 = router.submit(prompt, SamplingParams(
        max_new_tokens=16, temperature=0.9, top_k=7, seed=3))
    assert pr2.result(timeout=120) == ref


def test_health_reports_pids_and_load_observables(fleet):
    _cfg, _params, router, _m = fleet
    deadline = time.monotonic() + 30
    st = router.status()
    while time.monotonic() < deadline:
        st = router.status()
        live = [r for r in st["replicas"] if not r["retired"]]
        if all(r.get("pid") and "backlog_tokens" in r for r in live):
            break
        time.sleep(0.2)
    live = [r for r in st["replicas"] if not r["retired"]]
    assert st["healthy_replicas"] >= 2
    pids = {r["pid"] for r in live}
    assert len(pids) == len(live)            # distinct real processes
    assert os.getpid() not in pids           # none of them is us
    for r in live:
        assert r["programs_compiled"] is not None
        assert "tokens_per_s_ewma" in r
    snap = router.autoscale_snapshot()
    assert snap["healthy"] >= 2
    assert "backlog_tokens" in snap and "tokens_per_s" in snap


def test_proc_reload_rolls_through_workers(fleet):
    """Rolling hot-swap across the process boundary: both workers
    drain, rebuild from the new snapshot, and post-swap generations
    come from the NEW params exactly."""
    cfg, params, router, _m = fleet
    model = GPT(cfg)
    params_b = model.init({"params": jax.random.PRNGKey(7)},
                          np.zeros((1, 8), np.int64),
                          train=False)["params"]
    prompt = [1, 2, 3, 4]
    ref_b = _ref(params_b, cfg, prompt, 8, temperature=0.9, top_k=7,
                 seed=2)
    res = router.reload(params_b, weights_tag="v2",
                        drain_timeout_s=120.0)
    assert sorted(res["swapped"]) == sorted(
        r.id for r in router.replicas if r.healthy)
    # both replicas serve the new params (pin each one via dispatch)
    outs = []
    for seed_probe in range(4):
        pr = router.submit(prompt, SamplingParams(
            max_new_tokens=8, temperature=0.9, top_k=7, seed=2))
        outs.append((pr.replica_id, pr.result(timeout=120)))
    assert {rid for rid, _ in outs} == {
        r.id for r in router.replicas if r.healthy}
    for _rid, toks in outs:
        assert toks == ref_b
    assert router.status()["weight_reloads"] == 1


def test_kill9_mid_stream_splices_exact_and_respawns(fleet):
    """THE ISSUE-13 acceptance oracle, process edition: SIGKILL the
    worker subprocess serving a stream once >= 4 tokens have reached
    the client — the concatenated stream is byte-identical to an
    uncontended run, delivered inside the original deadline; the dead
    process leaves dispatch (and no zombie), and a ``scale_up``
    respawn (the autoscaler's move) restores the fleet."""
    cfg, params, router, metrics = fleet
    prompt = [1, 2, 3, 4, 5, 6]
    # 48 tokens, UNcoalesced chunks (one frame per decode step), kill
    # on the FIRST chunk: the worker dies with ~47 tokens ungenerated —
    # a warm worker can never outrun the kill into a no-op splice
    sp = SamplingParams(max_new_tokens=48, temperature=0.9, top_k=7,
                        seed=5)
    # reload (previous test) swapped to params_b — regenerate the
    # reference from what the fleet NOW serves: what matters is the
    # splice, not which weights
    model = GPT(cfg)
    params_b = model.init({"params": jax.random.PRNGKey(7)},
                          np.zeros((1, 8), np.int64),
                          train=False)["params"]
    ref = _ref(params_b, cfg, prompt, 48, temperature=0.9, top_k=7,
               seed=5)
    pr = router.submit(prompt, sp, deadline_s=120.0, coalesce_s=0.0)
    victim_pid, victim_rid = pr.pid, pr.replica_id
    got, killed = [], False
    t0 = time.perf_counter()
    for chunk in pr.stream(timeout=120):
        got.extend(chunk)
        if not killed:
            os.kill(victim_pid, signal.SIGKILL)
            killed = True
    wall = time.perf_counter() - t0
    assert killed, "stream finished before the kill landed"
    assert got == ref                       # byte-identical splice
    assert wall < 120.0                     # inside the deadline
    assert pr.failovers == 1
    assert pr.replica_id != victim_rid
    st = router.status()
    assert st["failovers"] >= 1
    victim = next(r for r in st["replicas"] if r["id"] == victim_rid)
    assert victim["dead"] is True and victim["healthy"] is False
    # the corpse is reaped (no zombie) once the router notices
    deadline = time.monotonic() + 30
    vrep = next(r for r in router.replicas if r.id == victim_rid)
    while time.monotonic() < deadline and vrep.proc.poll() is None:
        time.sleep(0.2)
    assert vrep.proc.poll() is not None
    # respawn — exactly what the autoscaler's floor rule does
    router.scale_up()
    router.wait_ready(n=2, timeout_s=240)
    st = router.status()
    assert st["healthy_replicas"] == 2
    assert st["replicas_spawned"] == 3      # 2 initial + 1 respawn
    assert metrics.headline()["replicas_spawned"] == 3
    # and the respawned fleet still serves exact streams
    pr = router.submit(prompt, sp)
    assert pr.result(timeout=120) == ref
