"""Worker for the 2-process ``Trainer.fit`` e2e test (VERDICT r3 #1).

Unlike ``_multihost_worker.py`` (which drives ``make_train_step``
directly), this runs the REAL flagship entry point — ``Trainer.fit`` —
in each process of a 2-process ``jax.distributed`` world: per-host data
loading through ``multihost.global_batch`` (each host materializes only
its own node's rows), replicated metric fetch, primary-gated CSV
logging, and a collective Orbax checkpoint written once.

Prints one JSON line with the full loss histories and a parameter
checksum; the test compares them across processes and against the same
fit in a single process.
"""

import json
import sys


def main() -> None:
    port, pid, tmp = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    import jax

    # This host's sitecustomize forces jax_platforms='axon,cpu'; the axon
    # plugin is a SINGLE-process backend, so with it as default both
    # workers would see jax.process_index() == 0 and process-index-
    # dependent code (Orbax's primary-writer election) would race on the
    # same files. Pin the default backend to the multi-process CPU world
    # — the analog of a real pod, where the default backend IS the
    # process-aware TPU client. Must run before any backend touch.
    jax.config.update("jax_platforms", "cpu")

    from gym_tpu.parallel import multihost

    assert multihost.initialize(
        coordinator_address=f"localhost:{port}", num_processes=2,
        process_id=pid,
    )
    import numpy as np

    from gym_tpu.data.gpt_datasets import ContiguousGPTTrainDataset
    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.strategy.diloco import DiLoCoStrategy
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.trainer import Trainer

    assert len(jax.devices("cpu")) == 2, "expected a 2-process world"

    rng = np.random.default_rng(7)
    data = rng.integers(0, 32, 2048, dtype=np.int64)
    ds = ContiguousGPTTrainDataset(data, block_size=8)
    cfg = GPTConfig(block_size=8, vocab_size=32, n_layer=1, n_head=2,
                    n_embd=16, dropout=0.0, bias=True)
    res = Trainer(GPT(cfg), ds, ds).fit(
        strategy=DiLoCoStrategy(OptimSpec("adamw", lr=1e-3), H=2),
        num_nodes=2, max_steps=4, batch_size=4, minibatch_size=2,
        val_size=4, val_interval=2, device="cpu",
        checkpoint_interval=2, save_dir=tmp + "/ckpt", run_name="mh",
        log_dir=tmp + "/logs", show_progress=False, seed=3,
    )
    checksum = float(sum(np.abs(np.asarray(x)).sum()
                         for x in jax.tree.leaves(res.params)))
    print(json.dumps({
        "pid": pid,
        "train": [round(float(l), 6) for _, l in res.history["train_loss"]],
        "local": [round(float(l), 6) for _, l in res.history["local_loss"]],
        "global": [round(float(l), 6)
                   for _, l in res.history["global_loss"]],
        "final": round(float(res.final_train_loss), 6),
        "checksum": round(checksum, 4),
    }), flush=True)


if __name__ == "__main__":
    main()
