"""Test harness: 16 virtual CPU devices — the JAX analog of the reference's
"multi-node on one box" (mp.spawn + Gloo over localhost, SURVEY §4).
16 (up from 8) so the full 4-axis sharding composition
(node × seq × model × expert, 2 each) runs in the default suite.

Must run before any JAX backend initialization. The environment's
sitecustomize registers an 'axon' TPU backend and forces
``jax_platforms='axon,cpu'``; we override back to cpu for tests.
"""

import os

os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=16 "
    + os.environ.get("XLA_FLAGS", "")
)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# jax 0.4.x's legacy shard_map lowers GSPMD-auto ('model'/'expert') axes
# alongside manual axes into a module the SPMD partitioner rejects
# ("PartitionId instruction is not supported"). The tp/ep COMPOSITION
# paths therefore need jax >= 0.5; pure-manual meshes are unaffected.
needs_partial_auto = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="GSPMD-auto mesh axes under shard_map need jax >= 0.5 "
           "(legacy partial-auto lowering emits unsupported PartitionId)")


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices("cpu")
    assert len(devs) >= 8, f"expected 8 cpu devices, got {len(devs)}"
    return devs[:8]


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
