"""Wire-corruption failover (ISSUE 20): a replica whose frames arrive
with flipped bits must NEVER deliver a wrong token — the per-frame crc
turns the corruption into a typed ``FrameCorruptError``, the router
marks the replica dead with that reason, and the stream completes
byte-exact through the surviving sibling.

Fault arming is PER-REPLICA via ``GYM_TPU_FAULTS_REPLICA_<id>`` (a
fleet-wide ``GYM_TPU_FAULTS`` would corrupt the failover target too).
The window ``@4+`` leaves the hello (hit 1) and the first health_ok
frames clean so ``wait_ready`` can see a healthy fleet before the
corruption strikes every later frame replica 0 sends.

Own module (not ``test_serve_procfleet``): the shared fleet fixture
there must stay corruption-free, and the env var has to be set BEFORE
the fleet spawns. Slow: two worker subprocesses each pay a jax import.
``scripts/ci_sdc.sh`` runs this file."""

import os
import tempfile

import numpy as np
import pytest

import jax

from gym_tpu.models.nanogpt import GPT, GPTConfig, generate_fast
from gym_tpu.serve.engine import SamplingParams
from gym_tpu.serve.metrics import ServeMetrics
from gym_tpu.serve.router import build_process_fleet

pytestmark = pytest.mark.slow

_ARM_VAR = "GYM_TPU_FAULTS_REPLICA_0"


@pytest.fixture()
def corrupt_fleet():
    os.environ[_ARM_VAR] = "wire.frame:bitflip=1@4+"
    cfg = GPTConfig(block_size=64, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True)
    model = GPT(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int64),
                        train=False)["params"]
    metrics = ServeMetrics(tempfile.mkdtemp(prefix="gym_tpu_sdcm_"))
    router = build_process_fleet(
        params, cfg, tempfile.mkdtemp(prefix="gym_tpu_sdcw_"),
        replicas=2, num_slots=2, metrics=metrics, no_warmup=True,
        max_restarts=0, log=lambda *a, **k: None)
    try:
        router.start()
        router.wait_ready(timeout_s=240)
        yield cfg, params, router
    finally:
        os.environ.pop(_ARM_VAR, None)
        router.close(drain_deadline_s=60)
        metrics.close()


def test_corrupt_wire_frames_fail_over_without_wrong_tokens(
        corrupt_fleet):
    cfg, params, router = corrupt_fleet
    prompt = [1, 2, 3, 4, 5, 6]
    ref = generate_fast(params, cfg, np.asarray(prompt)[None], 16,
                        temperature=0.9, top_k=7,
                        seed=3)[0, len(prompt):].tolist()
    got = []
    pr = router.submit(prompt, SamplingParams(
        max_new_tokens=16, temperature=0.9, top_k=7, seed=3))
    for chunk in pr.stream(timeout=120):
        got.extend(chunk)
    # never a wrong token: the stream is byte-exact despite replica 0
    # emitting corrupt frames for every post-readiness message
    assert got == ref, (got, ref)

    st = router.status()
    dead = [r for r in st["replicas"] if r.get("dead")]
    assert dead, st
    assert any("FrameCorruptError" in (r.get("death_reason") or "")
               for r in dead), st
    # the survivor is still healthy — the fleet did not collapse
    assert any(not r.get("dead") for r in st["replicas"]), st
