"""Multi-host init gating (``gym_tpu/parallel/multihost.py``): the gate must
decide from the environment ONLY — initializing on a single host would be
wrong, and touching the backend before ``jax.distributed.initialize`` would
poison the pod path (VERDICT r1 weak #7).
"""

import gym_tpu.parallel.multihost as mh


class _Recorder:
    def __init__(self):
        self.calls = []

    def initialize(self, **kw):
        self.calls.append(kw)


def _patch(monkeypatch, rec):
    monkeypatch.setattr(mh.jax, "distributed", rec)
    monkeypatch.setattr(mh.initialize, "_done", False, raising=False)


def test_single_host_is_noop(monkeypatch):
    rec = _Recorder()
    _patch(monkeypatch, rec)
    for var in ("GYM_TPU_NUM_PROCESSES", "TPU_WORKER_HOSTNAMES",
                "JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    assert mh.initialize() is False
    assert rec.calls == []


def test_env_hosts_triggers_init(monkeypatch):
    rec = _Recorder()
    _patch(monkeypatch, rec)
    monkeypatch.setenv("GYM_TPU_NUM_PROCESSES", "4")
    assert mh.initialize() is True
    assert len(rec.calls) == 1


def test_worker_hostnames_trigger_init(monkeypatch):
    rec = _Recorder()
    _patch(monkeypatch, rec)
    monkeypatch.delenv("GYM_TPU_NUM_PROCESSES", raising=False)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("MEGASCALE_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-a,host-b")
    assert mh.initialize() is True
    assert len(rec.calls) == 1
    # single hostname → still single host
    rec2 = _Recorder()
    _patch(monkeypatch, rec2)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-a")
    assert mh.initialize() is False
    assert rec2.calls == []


def test_explicit_args_forwarded(monkeypatch):
    rec = _Recorder()
    _patch(monkeypatch, rec)
    for var in ("GYM_TPU_NUM_PROCESSES", "TPU_WORKER_HOSTNAMES",
                "JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    assert mh.initialize("10.0.0.1:1234", 2, 1) is True
    assert rec.calls == [dict(coordinator_address="10.0.0.1:1234",
                              num_processes=2, process_id=1)]


def test_idempotent(monkeypatch):
    rec = _Recorder()
    _patch(monkeypatch, rec)
    monkeypatch.setenv("GYM_TPU_NUM_PROCESSES", "2")
    assert mh.initialize() is True
    assert mh.initialize() is True  # second call: no re-init
    assert len(rec.calls) == 1
