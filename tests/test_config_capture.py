"""Run-config capture (reference ``create_config``, ``exogym/utils.py:102-143``):
config.json must record a real param count and the model's hyperparameters
(VERDICT r1 missing #2), and the logging lr schedule must be host-only
(VERDICT r1 weak #5).
"""

import json
import os

import numpy as np

from gym_tpu import Trainer
from gym_tpu.models.nanogpt import GPT, GPTConfig
from gym_tpu.models.base import LossModel
from gym_tpu.data import ArrayDataset
from gym_tpu.strategy import DiLoCoStrategy, OptimSpec


def _char_dataset(n=512, block=32, seed=0):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, 65, size=(n, block)).astype(np.int64)
    tgt = np.roll(idx, -1, axis=-1)
    return ArrayDataset(idx, tgt)


def test_config_json_has_num_params_and_model_config(tmp_path):
    cfg = GPTConfig(block_size=32, vocab_size=65, n_layer=1, n_head=2,
                    n_embd=16, dropout=0.0)
    model = LossModel(GPT(cfg))
    res = Trainer(model.module, _char_dataset()).fit(
        strategy=DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=1e-3), H=2),
        num_nodes=2, max_steps=3, batch_size=8, minibatch_size=8,
        val_interval=0, show_progress=False,
        log_dir=str(tmp_path), run_name="cfgtest",
    )
    with open(os.path.join(tmp_path, "cfgtest", "config.json")) as f:
        config = json.load(f)
    # real param count: wte 65*16 + wpe 32*16 + block + ln_f
    assert isinstance(config["num_params"], int)
    assert config["num_params"] > 65 * 16
    mc = config["model_config"]["config"]
    assert mc["n_layer"] == 1 and mc["n_embd"] == 16 and mc["vocab_size"] == 65
    assert np.isfinite(res.final_train_loss)


def test_lr_at_is_host_only():
    """lr_at must not launch device computation (numpy twin of the
    schedule), and must match the traced jnp schedule exactly."""
    import jax.numpy as jnp

    s = DiLoCoStrategy(
        optim_spec=OptimSpec("adamw", lr=2e-3), H=10,
        lr_scheduler="lambda_cosine",
        lr_scheduler_kwargs={"warmup_steps": 5, "cosine_anneal": True},
    )
    s.finalize(max_steps=50)
    for step in (0, 1, 4, 5, 25, 49, 50):
        host = s.lr_at(step)
        traced = float(2e-3 * s._lr_scale(jnp.asarray(step)))
        assert abs(host - traced) < 1e-9, (step, host, traced)
    # the host evaluator is numpy end-to-end
    out = s._lr_scale_host(7)
    assert isinstance(out, np.ndarray) or isinstance(out, np.floating)
