"""Collective-toolkit tests: AxisCtx semantics over the node/vnode axes
must match the reference's torch.distributed collectives
(``exogym/strategy/communicate.py:63-75``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gym_tpu.parallel import NodeRuntime


@pytest.mark.parametrize("num_nodes", [1, 2, 8, 16])
def test_pmean_psum_node_index(num_nodes):
    rt = NodeRuntime.create(num_nodes)
    assert rt.n_phys * rt.n_virt == num_nodes

    def node_fn(x):
        ctx = rt.ctx
        return {
            "mean": ctx.pmean(x),
            "sum": ctx.psum(x),
            "idx": ctx.node_index(),
        }

    f = rt.compile(node_fn, donate_state=False)
    x = rt.shard_batch(np.arange(num_nodes, dtype=np.float32))
    out = jax.device_get(f(x))
    expect_mean = np.mean(np.arange(num_nodes))
    np.testing.assert_allclose(out["mean"], expect_mean, rtol=1e-6)
    np.testing.assert_allclose(out["sum"], expect_mean * num_nodes, rtol=1e-6)
    # node_index must be the global linear rank in state order
    np.testing.assert_array_equal(
        np.sort(out["idx"]), np.arange(num_nodes)
    )


@pytest.mark.parametrize("num_nodes", [2, 8])
def test_all_gather_order_matches_node_index(num_nodes):
    """all_gather's leading axis must be ordered by node_index — the
    contract strategies rely on (e.g. FedAvg islands, DeMo)."""
    rt = NodeRuntime.create(num_nodes)

    def node_fn(x):
        ctx = rt.ctx
        gathered = ctx.all_gather(x)
        my = ctx.node_index().astype(jnp.float32)
        return {"g": gathered, "my": my}

    f = rt.compile(node_fn, donate_state=False)
    # Each node holds a value equal to... we need node-dependent values:
    # feed the linear index itself as data.
    x = rt.shard_batch(np.arange(num_nodes, dtype=np.float32))
    out = jax.device_get(f(x))
    # Node k's data is whatever the runtime placed at global slot k; the
    # gather seen by every node must equal the global array in slot order.
    for k in range(num_nodes):
        np.testing.assert_array_equal(out["g"][k], np.asarray(out["g"][0]))
    # gathered[i] should be the value held by the node whose node_index==i
    idx_of_slot = out["my"].astype(int)  # slot -> node_index
    g0 = out["g"][0]
    for slot in range(num_nodes):
        assert g0[idx_of_slot[slot]] == x[slot]


def test_broadcast_from(devices8):
    rt = NodeRuntime.create(4)

    def node_fn(x):
        return rt.ctx.broadcast_from(x, src=2)

    f = rt.compile(node_fn, donate_state=False)
    x = rt.shard_batch(np.arange(4, dtype=np.float32))
    out = jax.device_get(f(x))
    # slot ordering == node_index ordering (verified above), so src=2 is x[2]
    np.testing.assert_array_equal(out, np.full(4, 2.0))


def test_more_nodes_than_devices():
    """64 simulated nodes on fewer devices: physical × vmapped folding."""
    n_dev = len(jax.devices())
    assert n_dev < 64
    # n_phys is the largest divisor of 64 that fits the devices (the
    # runtime's rule) — don't assume the device count divides 64
    expect_phys = max(d for d in range(1, n_dev + 1) if 64 % d == 0)
    rt = NodeRuntime.create(64)
    assert rt.n_phys == expect_phys and rt.n_virt == 64 // expect_phys
    assert rt.n_virt > 1  # the folding actually happens

    def node_fn(x):
        return rt.ctx.pmean(x)

    f = rt.compile(node_fn, donate_state=False)
    x = rt.shard_batch(np.arange(64, dtype=np.float32))
    out = jax.device_get(f(x))
    np.testing.assert_allclose(out, 31.5, rtol=1e-6)
