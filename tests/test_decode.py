"""KV-cache decode (GPTConfig.decode / generate_fast) — beyond-reference:
the reference's sampler re-runs the full context every token
(``example/nanogpt/nanogpt.py:410-439``).

Oracle: cached decode must produce the SAME logits as the full dense
forward at every position (teacher forcing), and greedy sampling must
match the parity ``generate``.
"""

import pytest
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from gym_tpu.models.nanogpt import (GPT, GPTConfig, generate, generate_fast,
                                    sample_logits)


def _setup():
    cfg = GPTConfig(block_size=32, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True)
    model = GPT(cfg)
    rng = jax.random.PRNGKey(0)
    idx = jax.random.randint(rng, (2, 12), 0, cfg.vocab_size)
    params = model.init({"params": rng}, idx, train=False)["params"]
    return cfg, model, params, idx


def test_cached_decode_logits_match_full_forward():
    cfg, model, params, idx = _setup()
    full = model.apply({"params": params}, idx, train=False)  # [B, T, V]

    dcfg = dataclasses.replace(cfg, decode=True)
    dmodel = GPT(dcfg)
    # prefill on the first 5 tokens: per-position logits must match
    pre, varsc = dmodel.apply({"params": params}, idx[:, :5],
                              train=False, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full[:, :5]),
                               rtol=1e-4, atol=1e-5)
    # then feed the rest one token at a time through the cache
    cache = varsc["cache"]
    for j in range(5, idx.shape[1]):
        lg, varsc = dmodel.apply({"params": params, "cache": cache},
                                 idx[:, j:j + 1], train=False,
                                 mutable=["cache"])
        cache = varsc["cache"]
        np.testing.assert_allclose(np.asarray(lg[:, 0]),
                                   np.asarray(full[:, j]),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_moe_decode_composes():
    """KV-cache decode over an MoE GPT: prefill logits equal the full
    forward, and generate_fast runs end-to-end (the MoE layer is
    position-independent, so only attention changes under decode)."""
    cfg = GPTConfig(block_size=32, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, n_experts=4, expert_topk=2)
    model = GPT(cfg)
    rng = jax.random.PRNGKey(0)
    idx = jax.random.randint(rng, (2, 10), 0, cfg.vocab_size)
    params = model.init({"params": rng}, idx, train=False)["params"]
    full = model.apply({"params": params}, idx, train=False)

    dmodel = GPT(dataclasses.replace(cfg, decode=True))
    pre, varsc = dmodel.apply({"params": params}, idx[:, :5],
                              train=False, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full[:, :5]),
                               rtol=1e-4, atol=1e-5)
    # single-token cached steps through the MoE blocks must also match
    cache = varsc["cache"]
    for j in range(5, idx.shape[1]):
        lg, varsc = dmodel.apply({"params": params, "cache": cache},
                                 idx[:, j:j + 1], train=False,
                                 mutable=["cache"])
        cache = varsc["cache"]
        np.testing.assert_allclose(np.asarray(lg[:, 0]),
                                   np.asarray(full[:, j]),
                                   rtol=1e-4, atol=1e-5)
    out = generate_fast(params, cfg, np.asarray(idx), 8, top_k=3, seed=1)
    assert out.shape == (2, 18)
    assert out.min() >= 0 and out.max() < cfg.vocab_size


@pytest.mark.slow
def test_generate_fast_matches_generate_greedy():
    cfg, model, params, idx = _setup()
    # top_k=1 → both samplers are argmax decoders; trajectories must agree
    slow = generate(params, cfg, np.asarray(idx), max_new_tokens=8,
                    top_k=1, seed=3)
    fast = generate_fast(params, cfg, np.asarray(idx), max_new_tokens=8,
                         top_k=1, seed=3)
    np.testing.assert_array_equal(slow, fast)


@pytest.mark.parametrize("variant", ["bias_false", "moe", "moe_bias_false"])
def test_cached_decode_matches_forward_variants(variant):
    """Cached decode == full dense forward at EVERY position, beyond the
    default config: bias=False drops every Dense/LayerNorm bias (a
    different param tree through the same cache path), and MoE configs
    route through `GPTConfig.is_moe_layer` blocks whose dispatch must be
    position-independent under single-token decode."""
    kw = dict(block_size=32, vocab_size=48, n_layer=2, n_head=2,
              n_embd=32, dropout=0.0)
    if "bias_false" in variant:
        kw["bias"] = False
    if "moe" in variant:
        kw.update(n_experts=4, expert_topk=2)
    cfg = GPTConfig(**kw)
    model = GPT(cfg)
    rng = jax.random.PRNGKey(1)
    idx = jax.random.randint(rng, (2, 11), 0, cfg.vocab_size)
    params = model.init({"params": rng}, idx, train=False)["params"]
    full = model.apply({"params": params}, idx, train=False)

    dmodel = GPT(dataclasses.replace(cfg, decode=True))
    pre, varsc = dmodel.apply({"params": params}, idx[:, :4],
                              train=False, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full[:, :4]),
                               rtol=1e-4, atol=1e-5)
    cache = varsc["cache"]
    for j in range(4, idx.shape[1]):
        lg, varsc = dmodel.apply({"params": params, "cache": cache},
                                 idx[:, j:j + 1], train=False,
                                 mutable=["cache"])
        cache = varsc["cache"]
        np.testing.assert_allclose(np.asarray(lg[:, 0]),
                                   np.asarray(full[:, j]),
                                   rtol=1e-4, atol=1e-5)


def test_generate_fast_overflow_raises_typed():
    """prompt + max_new_tokens past the cache is a ValueError (not a bare
    assert) that names `generate`'s context-crop fallback."""
    cfg, model, params, idx = _setup()
    with pytest.raises(ValueError, match="generate"):
        generate_fast(params, cfg, np.asarray(idx),
                      max_new_tokens=cfg.block_size)
    # the documented fallback: `generate` crops context and keeps going
    out = generate(params, cfg, np.asarray(idx)[:, :4],
                   max_new_tokens=cfg.block_size + 2, top_k=1)
    assert out.shape == (2, 4 + cfg.block_size + 2)


def test_top_p_greedy_parity_generate_vs_fast():
    """top_p small enough keeps only the argmax → both samplers become
    greedy decoders and their trajectories must agree exactly (parity of
    the numpy and jitted nucleus implementations)."""
    cfg, model, params, idx = _setup()
    slow = generate(params, cfg, np.asarray(idx), max_new_tokens=8,
                    top_p=1e-9, seed=5)
    fast = generate_fast(params, cfg, np.asarray(idx), max_new_tokens=8,
                         top_p=1e-9, seed=5)
    np.testing.assert_array_equal(slow, fast)


def test_top_p_determinism_and_support():
    """top_p sampling is deterministic per seed, and a tight nucleus
    restricts samples to the top of the distribution."""
    cfg, model, params, idx = _setup()
    a = generate_fast(params, cfg, np.asarray(idx), 6, temperature=0.9,
                      top_p=0.7, seed=11)
    b = generate_fast(params, cfg, np.asarray(idx), 6, temperature=0.9,
                      top_p=0.7, seed=11)
    np.testing.assert_array_equal(a, b)
    # crafted logits: nucleus p=0.6 keeps exactly the two dominant tokens
    logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.1, 0.06, 0.04]]))
    seen = {int(sample_logits(logits, jax.random.PRNGKey(s),
                              top_p=0.6)[0]) for s in range(64)}
    assert seen <= {0, 1} and len(seen) == 2


def test_decode_cache_overflow_poisons_output():
    """Writing past block_size must produce NaN logits (loud), not a
    silent clamp that overwrites recent K/V."""
    cfg, model, params, idx = _setup()
    dcfg = dataclasses.replace(cfg, decode=True)
    dmodel = GPT(dcfg)
    lg, varsc = dmodel.apply({"params": params}, idx[:, :8],
                             train=False, mutable=["cache"])
    cache = varsc["cache"]
    # fill to capacity, then one step beyond
    steps = cfg.block_size - 8
    tok = jnp.zeros((2, steps), jnp.int32)
    lg, varsc = dmodel.apply({"params": params, "cache": cache}, tok,
                             train=False, mutable=["cache"])
    assert np.all(np.isfinite(np.asarray(lg)))
    lg, _ = dmodel.apply(
        {"params": params, "cache": varsc["cache"]},
        jnp.zeros((2, 1), jnp.int32), train=False, mutable=["cache"])
    assert np.all(np.isnan(np.asarray(lg)))


def test_generate_fast_shape_and_determinism():
    cfg, model, params, idx = _setup()
    a = generate_fast(params, cfg, np.asarray(idx), max_new_tokens=6,
                      temperature=0.8, top_k=5, seed=9)
    b = generate_fast(params, cfg, np.asarray(idx), max_new_tokens=6,
                      temperature=0.8, top_k=5, seed=9)
    assert a.shape == (2, 18)
    np.testing.assert_array_equal(a, b)
    assert a.min() >= 0 and a.max() < cfg.vocab_size
    # prompt is preserved verbatim
    np.testing.assert_array_equal(a[:, :12], np.asarray(idx))
