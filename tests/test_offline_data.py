"""Offline REAL datasets (gym_tpu/data/offline.py): the discriminating
baseline data (VERDICT r1 #2 — synthetic fallbacks saturate to 0.000)."""

import os

import numpy as np
import pytest

from gym_tpu.data.offline import (CropAugmentedDataset, _upscale,
                                  build_docs_corpus, load_digits_mnist)


def test_upscale_bilinear_properties():
    const = np.full((2, 8, 8), 3.5, np.float32)
    up = _upscale(const, 28)
    assert up.shape == (2, 28, 28)
    np.testing.assert_allclose(up, 3.5, atol=1e-6)
    # monotone ramp stays monotone and preserves range
    ramp = np.tile(np.arange(8, dtype=np.float32)[None, :], (8, 1))[None]
    up = _upscale(ramp, 28)
    assert (np.diff(up[0], axis=1) >= -1e-6).all()
    assert up.min() >= 0.0 and up.max() <= 7.0 + 1e-6


def test_digits_loader_real_and_disjoint():
    pytest.importorskip("sklearn")
    tr = load_digits_mnist(True)
    va = load_digits_mnist(False)
    assert len(tr) + len(va) == 1797      # the full UCI digits set
    x, y = va.take(np.arange(8))
    assert x.shape == (8, 28, 28, 1) and x.dtype == np.float32
    assert y.dtype == np.int32 and set(np.unique(y)) <= set(range(10))
    # val images are deterministic; augmented train varies per call
    np.testing.assert_array_equal(x, va.take(np.arange(8))[0])
    a1, _ = tr.take(np.arange(8))
    a2, _ = tr.take(np.arange(8))
    assert not np.array_equal(a1, a2)
    # augmentation translates, never invents content: per-sample sums are
    # close (padding is background-valued)
    assert isinstance(tr, CropAugmentedDataset)


def test_digits_split_deterministic():
    pytest.importorskip("sklearn")
    a = load_digits_mnist(False)
    b = load_digits_mnist(False)
    xa, ya = a.take(np.arange(20))
    xb, yb = b.take(np.arange(20))
    np.testing.assert_array_equal(xa, xb)
    np.testing.assert_array_equal(ya, yb)


def test_docs_corpus_from_custom_root(tmp_path):
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "README.md").write_text(
        "Hello world. " * 400)  # > min_bytes
    (tmp_path / "pkg" / "mod.py").write_text(
        '"""' + "A module docstring long enough to be harvested by the "
        "corpus builder, with real English words. " * 60 + '"""\n'
    )
    out = build_docs_corpus(
        data_root=str(tmp_path / "cache"), min_bytes=1024,
        roots=(str(tmp_path),),
    )
    from gym_tpu.data.build_dataset import generate_char_vocab
    char_int, eos = generate_char_vocab()
    assert out.dtype == np.uint16
    assert (out < 66).all()
    assert (out == eos).sum() == 2        # one per source unit
    # cache hit returns identical stream
    again = build_docs_corpus(data_root=str(tmp_path / "cache"),
                              roots=(str(tmp_path),))
    np.testing.assert_array_equal(out, again)


def test_get_dataset_docs_integration(tmp_path):
    """The 'docs' dataset name flows through the standard selector."""
    # point the corpus at a small custom root via monkeypatching the cache:
    # build into the default data_root used by get_dataset
    from gym_tpu.data import get_dataset
    root = tmp_path / "data"
    (tmp_path / "src").mkdir()
    (tmp_path / "src" / "doc.md").write_text("The quick brown fox. " * 500)
    import gym_tpu.data.offline as off
    orig = off._DOC_ROOTS
    off._DOC_ROOTS = (str(tmp_path / "src"),)
    try:
        ds, vocab = get_dataset("docs", block_size=32, data_root=str(root))
    finally:
        off._DOC_ROOTS = orig
    assert vocab == 66
    x, y = ds.take(np.array([0, 5]))
    assert x.shape == (2, 32) and (y[:, :-1] == x[:, 1:]).all()


def test_augmentation_stream_resumes_exactly():
    """A resumed run must replay the exact augmentation crops of an
    uninterrupted one (the checkpoint subsystem's bit-reproducibility)."""
    pytest.importorskip("sklearn")
    a = load_digits_mnist(True)
    for _ in range(3):
        a.take(np.arange(4))
    snap = a.state()
    x_next, _ = a.take(np.arange(4))

    b = load_digits_mnist(True)
    b.load_state(snap)
    x_resumed, _ = b.take(np.arange(4))
    np.testing.assert_array_equal(x_next, x_resumed)


def test_mnist_example_uses_real_digits(monkeypatch):
    pytest.importorskip("sklearn")
    import importlib.util
    import sys
    # force the digits path even on machines with a torchvision MNIST copy
    monkeypatch.setitem(sys.modules, "torchvision", None)
    path = os.path.join(os.path.dirname(__file__), os.pardir, "examples",
                        "mnist.py")
    spec = importlib.util.spec_from_file_location("_mnist_example", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    ds = mod.load_mnist(False)
    assert len(ds) == 359      # sklearn digits val split, not synthetic
