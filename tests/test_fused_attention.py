"""Fused Pallas attention kernels: CPU parity via the Pallas interpreter.

The kernels (standard [B,H,T,D] and packed [B,T,C] layouts) carry
hand-derived flash-attention-2 backward math; these tests check forward
outputs and all three input gradients against the dense XLA path, on CPU,
by flipping the module's INTERPRET switch.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import gym_tpu.ops.fused_attention as fa
from gym_tpu.ops.attention import dense_causal_attention


@pytest.fixture(autouse=True)
def interpret_mode():
    old = fa.INTERPRET
    fa.INTERPRET = True
    yield
    fa.INTERPRET = old


B, H, T, D = 2, 3, 128, 16


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((B, H, T, D)), dtype)
        for _ in range(3)
    )


def test_fused_forward_matches_dense():
    q, k, v = _qkv()
    with jax.default_matmul_precision("highest"):
        out = fa.fused_causal_attention(q, k, v)
        ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_fused_grads_match_dense():
    q, k, v = _qkv(1)

    def loss_fused(q, k, v):
        return (fa.fused_causal_attention(q, k, v) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_causal_attention(q, k, v) ** 2).sum()

    with jax.default_matmul_precision("highest"):
        g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_fused_bf16_matches_dense_bf16():
    """The autocast path: kernels dot at native bf16 (f32 accumulate) and
    cast p/ds back to bf16 — must track the dense bf16 path within bf16
    noise. Covers the precision class the f32 tests can't see."""
    q, k, v = _qkv(6, jnp.bfloat16)

    def loss_fused(q, k, v):
        return (fa.fused_causal_attention(q, k, v)
                .astype(jnp.float32) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_causal_attention(q, k, v)
                .astype(jnp.float32) ** 2).sum()

    out = fa.fused_causal_attention(q, k, v)
    ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               atol=3e-2, rtol=3e-2)
    g1 = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=0.5, rtol=6e-2,
                                   err_msg=f"d{name} mismatch")


def _packed(seed=2):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((B, T, H * D)), jnp.float32)
        for _ in range(3)
    )


def _unpack(z):
    return z.reshape(B, T, H, D).transpose(0, 2, 1, 3)


def test_packed_forward_matches_dense():
    q, k, v = _packed()
    with jax.default_matmul_precision("highest"):
        out = fa.fused_causal_attention_packed(q, k, v, H)
        ref = dense_causal_attention(_unpack(q), _unpack(k), _unpack(v))
        ref = ref.transpose(0, 2, 1, 3).reshape(B, T, H * D)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_packed_grads_match_dense():
    q, k, v = _packed(3)

    def loss_packed(q, k, v):
        return (fa.fused_causal_attention_packed(q, k, v, H) ** 2).sum()

    def loss_dense(q, k, v):
        y = dense_causal_attention(_unpack(q), _unpack(k), _unpack(v))
        return (y.transpose(0, 2, 1, 3).reshape(B, T, H * D) ** 2).sum()

    with jax.default_matmul_precision("highest"):
        g1 = jax.grad(loss_packed, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=1e-3,
                                   err_msg=f"d{name} mismatch")


def test_batch_chunk_divides():
    # chunk helpers must return divisors of b
    for b in (1, 2, 4, 16, 48):
        for t in (128, 256, 1024):
            assert b % fa._batch_chunk(b, t) == 0
            assert b % fa._packed_chunk(b, t) == 0
