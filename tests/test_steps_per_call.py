"""steps_per_call: S steps per dispatch must be semantically identical to
S single dispatches — same batch order, same strategy schedule, same
parameters; only the host↔device cadence changes."""

import jax
import numpy as np

from gym_tpu import Trainer
from gym_tpu.strategy import DiLoCoStrategy, OptimSpec
from test_trainer_e2e import TinyLossModel, blobs


def _fit(spc, steps=7):
    ds = blobs(256, seed=8)
    return Trainer(TinyLossModel(), ds, None).fit(
        strategy=DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=1e-3), H=3),
        num_nodes=4, max_steps=steps, batch_size=16, minibatch_size=8,
        val_interval=0, show_progress=False, seed=13,
        steps_per_call=spc, log_dir="/tmp/gym_tpu_test_logs",
    )


def test_multi_call_matches_single():
    r1 = _fit(1)
    r3 = _fit(3)  # 2 multi calls + 1 remainder step on the 1-step program
    l1 = [l for _, l in r1.history["train_loss"]]
    l3 = [l for _, l in r3.history["train_loss"]]
    assert [s for s, _ in r3.history["train_loss"]] == list(range(7))
    np.testing.assert_allclose(l3, l1, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(r1.params), jax.tree.leaves(r3.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
