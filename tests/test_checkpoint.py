"""Checkpoint/resume (the reference's disabled subsystem, SURVEY §5.4).

Oracle: training N steps straight produces the same final parameters as
training k steps, "crashing", and resuming from the checkpoint for the
remaining N−k — including the data-iterator position and per-node RNG, so
the resumed run sees the exact same batch sequence.
"""

import pytest
import shutil

import jax
import numpy as np

from gym_tpu import Trainer
from gym_tpu.data import ArrayDataset
from gym_tpu.strategy import DiLoCoStrategy, OptimSpec

from test_trainer_e2e import TinyLossModel, blobs


def _fit(ds, max_steps, tmp, interval, strategy=None, run_name="ckpt_test",
         seed=11):
    if strategy is None:
        strategy = DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=1e-3),
                                  H=3)
    return Trainer(TinyLossModel(), ds, None).fit(
        strategy=strategy,
        num_nodes=4, max_steps=max_steps, batch_size=16, minibatch_size=8,
        val_interval=0, show_progress=False, seed=seed,
        checkpoint_interval=interval, save_dir=tmp, run_name=run_name,
        log_dir="/tmp/gym_tpu_test_logs",
    )


@pytest.mark.slow
def test_resume_matches_straight_run(tmp_path):
    ds = blobs(256, seed=5)
    straight_dir = str(tmp_path / "straight")
    resume_dir = str(tmp_path / "resume")

    res_straight = _fit(ds, 8, straight_dir, interval=100)  # never resumes

    _fit(ds, 4, resume_dir, interval=4)       # stops at step 4, ckpt saved
    res_resumed = _fit(ds, 8, resume_dir, interval=4)  # resumes 4 → 8

    for a, b in zip(jax.tree.leaves(res_straight.params),
                    jax.tree.leaves(res_resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
    # resumed run only logged steps 4..7
    steps = [s for s, _ in res_resumed.history["train_loss"]]
    assert min(steps) == 4 and max(steps) == 7

    shutil.rmtree(str(tmp_path), ignore_errors=True)


def test_keep_latest_pruning(tmp_path):
    ds = blobs(128, seed=6)
    d = str(tmp_path / "prune")
    _fit(ds, 6, d, interval=2)
    from gym_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(d, "ckpt_test")
    assert mgr.latest_step() == 6
    # max_to_keep=2 (ISSUE 2): older steps pruned, but TWO survive so a
    # corrupt newest checkpoint still leaves a valid fallback
    assert len(mgr.manager.all_steps()) == 2
    mgr.close()
    shutil.rmtree(str(tmp_path), ignore_errors=True)


def test_torn_only_step_dir_quarantined_and_resaveable(tmp_path):
    """The sweep's kill -9 resume path (ISSUE 3): when the ONLY step dir
    on disk is torn wreckage (Orbax lists bare numeric dirs even without
    their metadata), restore must quarantine it and raise
    CheckpointNotFoundError — the fresh-start signal — and the same step
    number must then be saveable again (not "Destination already
    exists")."""
    import os

    from gym_tpu.utils.checkpoint import (CheckpointManager,
                                          CheckpointNotFoundError)

    d = str(tmp_path / "unc")
    os.makedirs(os.path.join(d, "run", "4"))
    with open(os.path.join(d, "run", "4", "garbage"), "w") as f:
        f.write("partial write")
    mgr = CheckpointManager(d, "run", async_save=False,
                            retry_policy=_no_retries())
    state = {"w": np.zeros((2, 2), np.float32)}
    with pytest.raises(CheckpointNotFoundError, match="no valid"):
        mgr.restore(state)
    assert os.path.exists(os.path.join(d, "run", "4.corrupt-0"))
    mgr.save(4, state, {"pos": 0})
    assert mgr.latest_step() == 4
    step, _, data_state, _ = mgr.restore(state)
    assert step == 4 and data_state == {"pos": 0}
    mgr.close()
    shutil.rmtree(str(tmp_path), ignore_errors=True)


def _no_retries():
    from gym_tpu.utils.resilience import RetryPolicy
    return RetryPolicy(attempts=1)


@pytest.mark.slow
def test_resume_matches_straight_run_demo(tmp_path):
    """Same oracle with DeMo: its strategy state is the pooled chunk-layout
    momentum dict ('{a}x{b}' → [G, a, b]), a different pytree shape than
    the optax states — resume must restore it exactly."""
    from gym_tpu.strategy.demo import DeMoStrategy

    def demo():
        return DeMoStrategy(optim_spec=OptimSpec("sgd", lr=3e-3),
                            compression_topk=4, compression_chunk=8)

    ds = blobs(256, seed=7)
    straight = _fit(ds, 8, str(tmp_path / "s"), interval=100,
                    strategy=demo(), run_name="ckpt_demo", seed=13)
    _fit(ds, 4, str(tmp_path / "r"), interval=4,
         strategy=demo(), run_name="ckpt_demo", seed=13)
    resumed = _fit(ds, 8, str(tmp_path / "r"), interval=4,
                   strategy=demo(), run_name="ckpt_demo", seed=13)
    # guard against a vacuous pass: the second run must actually have
    # resumed at step 4 (a fresh same-seed 0→8 run would also match)
    steps = [s for s, _ in resumed.history["train_loss"]]
    assert min(steps) == 4 and max(steps) == 7
    for a, b in zip(jax.tree.leaves(straight.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
    shutil.rmtree(str(tmp_path), ignore_errors=True)


@pytest.mark.slow
def test_resume_matches_straight_run_pipeline(tmp_path):
    """Checkpoint/resume under pipeline parallelism: the pp TrainState
    (stage-sharded {'outer','stages'} params + mirrored strategy state)
    round-trips through Orbax, and a resumed fit(pp=2) reproduces the
    straight run's final parameters exactly."""
    from gym_tpu.data.gpt_datasets import ContiguousGPTTrainDataset
    from gym_tpu.models.nanogpt import GPT, GPTConfig

    rng = np.random.default_rng(6)
    data = rng.integers(0, 32, 4096, dtype=np.int64)
    ds = ContiguousGPTTrainDataset(data, block_size=16)
    cfg = GPTConfig(block_size=16, vocab_size=32, n_layer=4, n_head=2,
                    n_embd=32, dropout=0.0)

    def fit_pp(max_steps, tmp, interval):
        return Trainer(GPT(cfg), ds, None).fit(
            strategy=DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=1e-3),
                                    H=3),
            num_nodes=2, max_steps=max_steps, batch_size=8,
            minibatch_size=2, pp=2, val_interval=0, show_progress=False,
            seed=13, checkpoint_interval=interval, save_dir=tmp,
            run_name="ckpt_pp", log_dir="/tmp/gym_tpu_test_logs",
        )

    straight = fit_pp(6, str(tmp_path / "straight"), interval=100)
    fit_pp(3, str(tmp_path / "resume"), interval=3)
    resumed = fit_pp(6, str(tmp_path / "resume"), interval=3)

    for a, b in zip(jax.tree.leaves(straight.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)
    steps = [s for s, _ in resumed.history["train_loss"]]
    assert min(steps) == 3 and max(steps) == 5
    shutil.rmtree(str(tmp_path), ignore_errors=True)


@pytest.mark.slow
def test_cross_topology_restore_pp2_tp2_to_pp1(tmp_path):
    """Cross-topology restore (VERDICT r3 #6): checkpoints are written in
    the CANONICAL plain-GPT layout, so a run saved under fit(pp=2, tp=2)
    resumes at pp=1 with a continuous trajectory. Oracle: a straight
    pp=1 run 0→6 equals [pp=2×tp=2 run 0→3 → checkpoint → pp=1 resume
    3→6] to float tolerance (pipelining/sharding are schedules, not
    algorithm changes — pinned by the pp parity tests)."""
    import pytest

    from gym_tpu.data.gpt_datasets import ContiguousGPTTrainDataset
    from gym_tpu.models.nanogpt import GPT, GPTConfig

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices (node=2 x model=2 x pipe=2)")
    if not hasattr(jax, "shard_map"):
        # jax 0.4.x: the manual('pipe') x GSPMD-auto('model') composition
        # trips "PartitionId instruction is not supported for SPMD
        # partitioning" in the legacy partial-auto shard_map partitioner
        pytest.skip("pp x tp partial-auto shard_map needs jax >= 0.5")

    rng = np.random.default_rng(8)
    data = rng.integers(0, 32, 4096, dtype=np.int64)
    ds = ContiguousGPTTrainDataset(data, block_size=16)
    cfg = GPTConfig(block_size=16, vocab_size=32, n_layer=4, n_head=2,
                    n_embd=32, dropout=0.0)

    def fit_any(max_steps, tmp, interval, pp=1, tp=1):
        return Trainer(GPT(cfg), ds, None).fit(
            strategy=DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=1e-3),
                                    H=3),
            num_nodes=2, max_steps=max_steps, batch_size=8,
            minibatch_size=2, pp=pp, tp=tp, val_interval=0,
            show_progress=False, seed=17, checkpoint_interval=interval,
            save_dir=tmp, run_name="ckpt_xtopo",
            log_dir="/tmp/gym_tpu_test_logs",
        )

    with jax.default_matmul_precision("highest"):
        straight = fit_any(6, str(tmp_path / "straight"), interval=100)
        fit_any(3, str(tmp_path / "resume"), interval=3, pp=2, tp=2)
        resumed = fit_any(6, str(tmp_path / "resume"), interval=3)  # pp=1

    steps = [s for s, _ in resumed.history["train_loss"]]
    assert min(steps) == 3 and max(steps) == 5  # genuinely resumed
    losses = [l for _, l in resumed.history["train_loss"]]
    assert np.all(np.isfinite(losses))
    for a, b in zip(jax.tree.leaves(straight.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)
    shutil.rmtree(str(tmp_path), ignore_errors=True)
