"""End-to-end slice (SURVEY §7 step 3): CNN + multi-node SimpleReduce on the
CPU device mesh. The oracle mirrors the reference's own validation approach
(SURVEY §4): convergence, not bitwise asserts. Cheap mechanics tests use a
tiny MLP to keep CPU compile time down; one test exercises the full
reference-parity CNN."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from gym_tpu import Trainer
from gym_tpu.data import ArrayDataset
from gym_tpu.models import MnistLossModel
from gym_tpu.strategy import OptimSpec, SimpleReduceStrategy


class TinyLossModel(nn.Module):
    """Small classifier for fast mechanics tests."""

    @nn.compact
    def __call__(self, batch, train: bool = True):
        x, y = batch
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        logits = nn.Dense(10)(x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), y
        ).mean()


def blobs(n=256, d=8, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    x = rng.normal(0, 0.3, size=(n, d, d)).astype(np.float32)
    for i, y in enumerate(labels):
        x[i, y % d, :] += 1.5
    return ArrayDataset(x, labels)


def synthetic_mnist(n=256, seed=0):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    imgs = rng.normal(0, 0.1, size=(n, 28, 28, 1)).astype(np.float32)
    for i, y in enumerate(labels):
        imgs[i, (y * 2) : (y * 2 + 4), 10:18, 0] += 1.0
    return ArrayDataset(imgs, labels)


def test_tiny_multinode_loss_decreases():
    ds = blobs(512)
    res = Trainer(TinyLossModel(), ds, blobs(64, seed=1)).fit(
        strategy=SimpleReduceStrategy(OptimSpec("adamw", lr=1e-3)),
        num_nodes=8, max_steps=30, batch_size=32, minibatch_size=16,
        val_size=32, val_interval=10, show_progress=False,
        log_dir="/tmp/gym_tpu_test_logs",
    )
    first = res.history["train_loss"][0][1]
    last = np.mean([l for _, l in res.history["train_loss"][-5:]])
    assert last < first, (first, last)
    assert len(res.history["local_loss"]) >= 2
    comm = [c for _, c in res.history["comm_bytes"]]
    assert all(c > 0 for c in comm)
    for leaf in jax.tree.leaves(res.params):
        assert np.all(np.isfinite(leaf))


@pytest.mark.slow
def test_mnist_cnn_e2e():
    """Reference-parity CNN (example/mnist.py architecture) trains 2-node
    SimpleReduce without NaNs and improves."""
    ds = synthetic_mnist(256)
    res = Trainer(MnistLossModel(), ds, synthetic_mnist(64, seed=1)).fit(
        strategy=SimpleReduceStrategy(
            optim_spec=OptimSpec("adamw", lr=3e-4, weight_decay=1e-4)
        ),
        num_nodes=2, max_steps=10, batch_size=16, minibatch_size=16,
        val_size=16, val_interval=5, show_progress=False,
        log_dir="/tmp/gym_tpu_test_logs",
    )
    losses = [l for _, l in res.history["train_loss"]]
    assert np.all(np.isfinite(losses))
    assert np.mean(losses[-3:]) < losses[0] + 1.0  # no blow-up
    for leaf in jax.tree.leaves(res.params):
        assert np.all(np.isfinite(leaf))


def test_factory_dataset_convention():
    """Per-node dataset factories f(rank, num_nodes, is_val) -> dataset
    (reference train_node.py:61-78)."""

    def factory(rank, num_nodes, is_val):
        return blobs(64, seed=100 + rank + (1000 if is_val else 0))

    res = Trainer(TinyLossModel(), factory, factory).fit(
        strategy=SimpleReduceStrategy(OptimSpec("sgd", lr=0.05)),
        num_nodes=4, max_steps=6, batch_size=16, minibatch_size=16,
        val_size=16, val_interval=3, show_progress=False,
        log_dir="/tmp/gym_tpu_test_logs",
    )
    assert np.isfinite(res.final_train_loss)


def test_fit_init_params_hook():
    """fit(init_params=...) starts from the GIVEN weights — the analog of
    the reference training whatever weights the passed nn.Module holds
    (fine-tuning / ported checkpoints / identical-init comparisons).
    Pinned two ways: with lr=0 the given params pass through the whole
    fit unchanged; a warm start from a trained result opens at a lower
    loss than the cold-start run did."""
    ds = blobs(256)

    def fit(**kw):
        return Trainer(TinyLossModel(), ds).fit(
            num_nodes=2, batch_size=32, minibatch_size=32, val_size=0,
            val_interval=0, show_progress=False,
            log_dir="/tmp/gym_tpu_test_logs", **kw)

    base = fit(strategy=SimpleReduceStrategy(OptimSpec("sgd", lr=0.05)),
               max_steps=6)

    frozen = fit(strategy=SimpleReduceStrategy(OptimSpec("sgd", lr=0.0)),
                 max_steps=1, init_params=base.params)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        frozen.params, base.params)

    warm = fit(strategy=SimpleReduceStrategy(OptimSpec("sgd", lr=0.05)),
               max_steps=2, init_params=base.params)
    assert warm.history["train_loss"][0][1] \
        < base.history["train_loss"][0][1]


def test_replica_correlation_observable():
    """Reference `_correlation_calculation` analog (dead code there,
    exogym/train_node.py:498-571): mean pairwise Pearson correlation of
    per-node params. Under DiLoCo the replicas drift between outer syncs
    (corr < 1) and snap back to exactly-correlated at the H boundary."""
    from gym_tpu.strategy import DiLoCoStrategy, OptimSpec

    res = Trainer(TinyLossModel(), blobs(512)).fit(
        strategy=DiLoCoStrategy(OptimSpec("adamw", lr=3e-2), H=5),
        num_nodes=4, max_steps=11, batch_size=32, minibatch_size=32,
        val_size=0, val_interval=0, correlation_interval=1,
        show_progress=False, log_dir="/tmp/gym_tpu_test_logs",
    )
    corr = dict(res.history["avg_model_correlation"])
    assert len(corr) >= 10
    assert all(np.isfinite(v) and v <= 1.0 + 1e-9 for v in corr.values())
    # step 5 ran the outer sync at t=5 (H gate): correlation logged at
    # step 6 (post-sync params) is exactly 1 up to float eps; mid-drift
    # values are strictly below it
    assert corr[6] > 0.999999
    drift = [corr[s] for s in (3, 4, 5)]
    assert min(drift) < corr[6]
