"""Trace-driven serving simulator (ISSUE 15): traces, replay,
cost-model policy invariants, the serve.csv schema satellites, and a
sim-vs-live agreement smoke.

Acceptance oracles pinned here:

- trace generators are SEEDED and bit-reproducible; the on-disk format
  roundtrips exactly (a trace is an artifact both simulator arms must
  agree on).
- the replayer is OPEN-LOOP: a slow server does not slow the offered
  arrival process (non-coordinated omission).
- the cost model runs the REAL ``AutoscaleController`` and honors its
  contract under generated traffic: scale-up latency bounded by
  patience × interval, never below the floor, cooldown respected.
- ``serve.csv`` satellites: request rows carry ``t_submit`` (arrival
  process reconstructible from disk), autoscale ticks persist as audit
  rows, and ``read_headline`` stays tolerant of OLD headers — pinned
  against a hand-written pre-servesim CSV.
- one small sim-vs-live smoke: the cost model's report against a real
  single-replica fleet replay of the same trace (the full-size
  agreement contract lives in ``bench.py --tracesim-only``).
"""

import csv
import os
import tempfile
import time

import numpy as np
import pytest

from gym_tpu.serve.autoscale import AutoscaleController, AutoscalePolicy
from gym_tpu.serve.metrics import ServeMetrics, read_headline
from gym_tpu.servesim import (FleetCostModel, Outcome, RequestEvent,
                              ServiceProfile, bursty_trace,
                              diurnal_trace, flash_crowd_trace,
                              load_trace, make_trace, prompt_tokens,
                              replay, replay_from_serve_csv, save_trace,
                              slo_report, trace_stats)

# ---------------------------------------------------------------------------
# traces


def test_traces_seeded_and_roundtrip(tmp_path):
    a = diurnal_trace(duration_s=20, base_rps=3.0, seed=7)
    b = diurnal_trace(duration_s=20, base_rps=3.0, seed=7)
    c = diurnal_trace(duration_s=20, base_rps=3.0, seed=8)
    assert a == b                      # same seed, same trace, exactly
    assert a != c
    path = str(tmp_path / "t.csv")
    assert load_trace(save_trace(path, a)) == a     # exact roundtrip
    # a non-trace CSV is refused, not misparsed
    bad = str(tmp_path / "bad.csv")
    with open(bad, "w") as f:
        f.write("x,y\n1,2\n")
    with pytest.raises(ValueError, match="not a gym_tpu trace"):
        load_trace(bad)


def test_trace_families_shape():
    for family in ("diurnal", "bursty", "flash_crowd"):
        ev = make_trace(family, seed=1, duration_s=30,
                        deadline_s=5.0, deadline_frac=0.5,
                        prefix_groups=3)
        st = trace_stats(ev)
        assert st["requests"] > 10, (family, st)
        assert 0 < st["with_deadline"] < st["requests"]
        assert st["prefix_grouped"] > 0
        assert all(e.arrival_s >= 0 for e in ev)
        assert ev == sorted(ev, key=lambda e: e.arrival_s)
    # the flash visibly lifts the rate inside its window
    fl = flash_crowd_trace(duration_s=40, base_rps=1.0, flash_at_s=10,
                           flash_mult=10, flash_len_s=10, seed=2)
    inside = sum(1 for e in fl if 10 <= e.arrival_s < 20)
    outside = sum(1 for e in fl if e.arrival_s < 10)
    assert inside > 3 * max(1, outside)


def test_prefix_groups_share_prompt_prefix():
    e1 = RequestEvent(0.0, prompt_len=20, max_new=8, prefix_group=4,
                      seed=1)
    e2 = RequestEvent(1.0, prompt_len=16, max_new=8, prefix_group=4,
                      seed=2)
    e3 = RequestEvent(2.0, prompt_len=20, max_new=8, prefix_group=5,
                      seed=3)
    p1 = prompt_tokens(e1, 48)
    p2 = prompt_tokens(e2, 48)
    p3 = prompt_tokens(e3, 48)
    n = min(int(20 * 0.5), int(16 * 0.5))
    assert p1[:n].tolist() == p2[:n].tolist()     # same group: shared
    assert p1[:n].tolist() != p3[:n].tolist()     # different group
    # deterministic: the prompt is a pure function of the event
    assert prompt_tokens(e1, 48).tolist() == p1.tolist()


# ---------------------------------------------------------------------------
# replay


def test_replay_is_open_loop():
    """A slow client must not slow the arrival process: with 0.3s
    service and arrivals every 50ms, submits still land near their
    scheduled times (closed-loop would serialize to ~0.3s apart)."""
    events = [RequestEvent(i * 0.05, 4, 4, seed=i) for i in range(5)]
    t_subs = {}

    def client(ev, t0):
        t_subs[ev.seed] = time.perf_counter() - t0
        time.sleep(0.3)
        return Outcome(index=ev.seed, arrival_s=ev.arrival_s,
                       t_submit=t_subs[ev.seed], status="done",
                       tokens=ev.max_new, max_new=ev.max_new)

    outs = replay(events, client, time_scale=1.0)
    assert len(outs) == 5 and all(o.status == "done" for o in outs)
    # last arrival scheduled 0.2s in; open loop keeps it under ~0.5s
    # (closed loop would be >= 4 * 0.3 = 1.2s)
    assert t_subs[4] < 0.6, t_subs


def test_slo_report_counts_and_attainment():
    outs = [
        Outcome(0, 0.0, 0.0, "done", ttft_s=0.1, latency_s=0.5,
                tokens=8, max_new=8),
        Outcome(1, 0.1, 0.1, "done", ttft_s=2.0, latency_s=3.0,
                tokens=8, max_new=8),
        Outcome(2, 0.2, 0.2, "rejected", max_new=8),
        Outcome(3, 0.3, 0.3, "shed", tokens=2, max_new=8),
    ]
    rep = slo_report(outs, slo_ttft_s=1.0, replica_seconds=12.0,
                     wall_s=4.0)
    assert rep["requests"] == 4 and rep["done"] == 2
    assert rep["shed_rate"] == 0.5          # rejected + shed over 4
    assert rep["slo_attainment"] == 0.25    # only the 0.1s TTFT one
    assert rep["replica_seconds"] == 12.0
    assert rep["tokens_out"] == 18


# ---------------------------------------------------------------------------
# serve.csv satellites: t_submit + autoscale audit rows


class _FakeReq:
    def __init__(self, submit_t, tokens=4, prompt=4):
        self.id = 1
        self.error = None
        self.exception = None
        self.tokens = list(range(tokens))
        self.prompt = np.zeros(prompt, np.int32)
        self.submit_t = submit_t
        self.ttft_s = 0.05
        self.avg_token_latency_s = 0.01


def test_serve_csv_t_submit_and_autoscale_rows(tmp_path):
    m = ServeMetrics(str(tmp_path))
    # three requests submitted at known offsets from the collector's t0
    for dt in (0.5, 1.25, 2.0):
        m.request_done(_FakeReq(m._t0 + dt), queue_depth=0,
                       active_slots=1)
    m.autoscale_tick(healthy=1, starting=0, backlog_tokens=512.0,
                     tokens_per_s=100.0, decision=+1,
                     reason="up: drain_s=5.12 over for 2 tick(s)")
    m.autoscale_tick(healthy=2, starting=0, backlog_tokens=0.0,
                     tokens_per_s=200.0, decision=0,
                     reason="hold: drain_s=0.00 over=0/2 under=1/8")
    head_live = m.headline()
    m.close()
    assert head_live["autoscale"] == {"ticks": 2, "ups": 1, "downs": 0}

    path = os.path.join(str(tmp_path), "serve.csv")
    with open(path, newline="") as f:
        rows = list(csv.DictReader(f))
    req_rows = [r for r in rows if r["kind"] == "request"]
    subs = [float(r["t_submit"]) for r in req_rows]
    assert subs == pytest.approx([0.5, 1.25, 2.0], abs=0.01)
    as_rows = [r for r in rows if r["kind"] == "autoscale"]
    assert [r["status"] for r in as_rows] == ["up", "hold"]
    assert as_rows[0]["as_healthy"] == "1"
    assert as_rows[0]["as_backlog_tokens"] == "512.0"
    assert as_rows[0]["as_reason"].startswith("up:")
    assert as_rows[0]["tokens_per_s"] == "100.00"

    # read_headline folds the audit rows + ignores them as requests
    head = read_headline(path)
    assert head["requests_done"] == 3
    assert head["autoscale"] == {"ticks": 2, "ups": 1, "downs": 0}

    # and the trace satellite: arrivals reconstruct EXACTLY from
    # t_submit (normalized to the first arrival)
    tr = replay_from_serve_csv(path)
    assert [e.arrival_s for e in tr] == pytest.approx([0.0, 0.75, 1.5],
                                                      abs=0.01)
    assert all(e.max_new == 4 for e in tr)


def test_read_headline_tolerates_pre_servesim_header(tmp_path):
    """The schema-bump contract, pinned: a serve.csv written BEFORE the
    t_submit/autoscale columns existed still aggregates — and the trace
    replayer falls back to the completion stamp."""
    path = str(tmp_path / "old.csv")
    old_header = ["ts_s", "kind", "request_id", "status", "queue_depth",
                  "active_slots", "prompt_tokens", "new_tokens",
                  "ttft_s", "avg_token_latency_s", "cum_tokens",
                  "tokens_per_s", "kv_blocks_in_use",
                  "prefix_hit_blocks", "spec_accept_rate", "replica_id",
                  "programs_built", "programs_compiled",
                  "program_compile_s", "weights_dtype", "kv_dtype",
                  "pid"]
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(old_header)
        w.writerow(["1.0", "request", "0", "done", "0", "1", "4", "8",
                    "0.05", "0.01", "8", "8.0", "", "", "", "", "", "",
                    "", "", "", ""])
        w.writerow(["2.0", "request", "1", "shed", "0", "1", "4", "0",
                    "", "", "8", "4.0", "", "", "", "", "", "", "", "",
                    "", ""])
    head = read_headline(path)
    assert head["requests_done"] == 1
    assert head["requests_shed"] == 1
    assert "autoscale" not in head
    tr = replay_from_serve_csv(path)
    assert len(tr) == 2 and tr[1].arrival_s == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# controller reasons + cost-model policy invariants


def test_controller_reasons():
    c = AutoscaleController(AutoscalePolicy(
        min_replicas=1, max_replicas=4, up_patience=2, cooldown=3))
    assert c.tick(0, 0, 0.0, None) == 1
    assert c.last_reason.startswith("floor")
    assert c.tick(1, 0, 0.0, None) == 0
    assert c.last_reason.startswith("cooldown")
    c2 = AutoscaleController(AutoscalePolicy(
        min_replicas=1, max_replicas=4, up_patience=2, cooldown=0,
        up_drain_s=2.0, down_drain_s=0.5))
    assert c2.tick(1, 0, 1000.0, 100.0) == 0
    assert c2.last_reason.startswith("hold: drain_s=10.00")
    assert c2.tick(1, 0, 1000.0, 100.0) == 1
    assert c2.last_reason.startswith("up:")


_PROFILE = ServiceProfile(tokens_per_s=120.0, num_slots=4,
                          request_overhead_s=0.05, startup_s=4.0)


def _flash():
    return flash_crowd_trace(duration_s=60, base_rps=2.0,
                             flash_at_s=20, flash_mult=8,
                             flash_len_s=10, seed=3,
                             prompt_lens=(8, 32), max_news=(12, 32))


def test_cost_model_scale_up_latency_bounded():
    """Under a flash crowd the modeled controller must spawn within
    (up_patience + 1) ticks of the backlog crossing the watermark —
    the scale-up-latency contract the policy advertises."""
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4,
                          up_drain_s=2.0, down_drain_s=0.25,
                          up_patience=2, down_patience=8, cooldown=4)
    res = FleetCostModel(_PROFILE, pol, initial_replicas=1).run(_flash())
    ups = [e for e in res.autoscale_log if e["decision"] > 0]
    assert ups, "flash crowd never triggered a scale-up"
    first_over = next(e["t"] for e in res.autoscale_log
                      if e["tokens_per_s"]
                      and e["backlog_tokens"] / e["tokens_per_s"] > 2.0)
    # patience consecutive over-ticks + the decision tick itself
    assert ups[0]["t"] - first_over <= (pol.up_patience + 1) * 1.0
    assert res.max_replicas_seen > 1


def test_cost_model_never_below_floor_and_cooldown():
    pol = AutoscalePolicy(min_replicas=2, max_replicas=4,
                          up_drain_s=2.0, down_drain_s=0.25,
                          up_patience=1, down_patience=4, cooldown=3)
    res = FleetCostModel(_PROFILE, pol, initial_replicas=2).run(_flash())
    assert all(e["healthy"] + e["starting"] >= 2
               for e in res.autoscale_log), "went below the floor"
    # cooldown: non-hold decisions at least `cooldown` ticks apart
    acts = [e["t"] for e in res.autoscale_log if e["decision"] != 0]
    gaps = [b - a for a, b in zip(acts, acts[1:])]
    assert all(g >= pol.cooldown for g in gaps), (acts, gaps)


def test_cost_model_diurnal_scales_down_after_peak():
    pol = AutoscalePolicy(min_replicas=1, max_replicas=4,
                          up_drain_s=2.0, down_drain_s=0.5,
                          up_patience=1, down_patience=4, cooldown=2)
    tr = diurnal_trace(duration_s=90, base_rps=6.0, amplitude=0.9,
                       seed=4, prompt_lens=(8, 32), max_news=(12, 32))
    res = FleetCostModel(_PROFILE, pol, initial_replicas=1).run(tr)
    assert res.spawns >= 1
    assert res.retires >= 1, "never scaled back down after the trough"
    # conservation: every offered request has exactly one outcome
    rep = res.report()
    assert (rep["done"] + rep["rejected"] + rep["shed"]
            + rep["failed"]) == rep["requests"] == len(tr)


def test_cost_model_more_replicas_better_tail():
    """Monotonicity sanity: a 4-replica fixed fleet cannot have a worse
    p99 TTFT than a 1-replica fixed fleet on the same overload trace."""
    tr = bursty_trace(duration_s=60, calm_rps=2.0, burst_rps=16.0,
                      seed=5, prompt_lens=(8, 32), max_news=(12, 32))
    r1 = FleetCostModel(_PROFILE, initial_replicas=1,
                        autoscale=False).run(tr).report()
    r4 = FleetCostModel(_PROFILE, initial_replicas=4,
                        autoscale=False).run(tr).report()
    assert r4["ttft_p99_s"] <= r1["ttft_p99_s"]
    assert r4["replica_seconds"] > r1["replica_seconds"]


def test_cost_model_deadline_sheds():
    """Deadlined requests under deep overload shed (admission or
    queue-sweep), and a shed request never reports full tokens."""
    tr = flash_crowd_trace(duration_s=30, base_rps=2.0, flash_at_s=5,
                           flash_mult=20, flash_len_s=8, seed=6,
                           prompt_lens=(8, 32), max_news=(16, 48),
                           deadline_s=1.0, deadline_frac=1.0)
    rep = FleetCostModel(_PROFILE, initial_replicas=1,
                         autoscale=False).run(tr).report()
    assert rep["shed_rate"] > 0.2, rep
    assert rep["done"] + rep["rejected"] + rep["shed"] == rep["requests"]


# ---------------------------------------------------------------------------
# sweep + gate (tiny grid, resumable)


def test_serve_sweep_resumable_and_frontier(tmp_path):
    from gym_tpu.servesim.sweep import (ServeSweepConfig, grid,
                                        run_sweep)
    cfg = ServeSweepConfig(
        traces=["flash_crowd"], up_drain_s=[2.0], down_drain_s=[0.5],
        up_patience=[1, 2], cooldown=[2], bounds=[(1, 2), (1, 4)],
        duration_s=40.0, out=str(tmp_path / "sweep"))
    rows = run_sweep(cfg)
    assert len(rows) == len(grid(cfg)) == 4
    out = str(tmp_path / "sweep")
    assert os.path.exists(os.path.join(out, "frontier.csv"))
    assert os.path.exists(os.path.join(out, "report.md"))
    with open(os.path.join(out, "frontier.csv"), newline="") as f:
        frows = list(csv.DictReader(f))
    assert len(frows) == 4
    assert any(r["on_frontier"] == "True" for r in frows)
    # resumability: a rerun serves every cell from its marker
    rows2 = run_sweep(cfg)
    assert rows2 == rows
    # a changed workload invalidates the cache: cells re-measure under
    # the new trace (more seconds -> more offered requests)
    import dataclasses
    cfg3 = dataclasses.replace(cfg, duration_s=60.0)
    rows3 = run_sweep(cfg3)
    assert all(r3["requests"] > r["requests"]
               for r, r3 in zip(rows, rows3))


def test_frontier_gate_record_and_check(tmp_path, monkeypatch):
    """The committed-baseline contract: the gate's COMPARISON path
    passes on an unchanged frontier, fails when the cheapest
    SLO-meeting cost drifts past the ceiling or a family stops meeting
    the SLO at all — exercised via a canned frontier so the grid's
    size doesn't gate the gate's own logic."""
    import copy
    import json as _json

    from gym_tpu.servesim import frontier_gate as fg
    from gym_tpu.servesim.sweep import ServeSweepConfig
    small = ServeSweepConfig(
        traces=["flash_crowd"], up_drain_s=[2.0], down_drain_s=[0.5],
        up_patience=[1], cooldown=[2], bounds=[(1, 4)],
        duration_s=40.0, slo_attainment_target=0.5,
        out=str(tmp_path / "unused"))
    cur = fg.fast_frontier(small)
    best = cur["families"]["flash_crowd"]
    assert best is not None and best["replica_seconds"] > 0
    # determinism: the gate's whole premise
    assert fg.fast_frontier(small) == cur

    monkeypatch.setattr(fg, "fast_frontier", lambda cfg=None: cur)
    base = str(tmp_path / "base.json")
    assert fg.main(["--record", base]) == 0
    assert fg.main(["--baseline", base]) == 0          # unchanged: OK
    assert fg.main(["--baseline",
                    str(tmp_path / "missing.json")]) == 2
    # poisoned baseline: cheaper than reachable -> regression
    poisoned = copy.deepcopy(cur)
    poisoned["families"]["flash_crowd"]["replica_seconds"] *= 0.5
    with open(base, "w") as f:
        _json.dump(poisoned, f)
    assert fg.main(["--baseline", base]) == 1
    # baseline met the SLO but the current frontier no longer does
    with open(base, "w") as f:
        _json.dump(cur, f)
    broken = copy.deepcopy(cur)
    broken["families"]["flash_crowd"] = None
    monkeypatch.setattr(fg, "fast_frontier", lambda cfg=None: broken)
    assert fg.main(["--baseline", base]) == 1


# ---------------------------------------------------------------------------
# sim-vs-live smoke (one small trace against a REAL fleet)


def test_sim_vs_live_smoke():
    """The agreement smoke on one tiny feasible trace: the cost model
    over a calibrated profile predicts the same outcome counts and a
    p99 TTFT in the same regime as a real single-replica fleet replay.
    (The overload-regime agreement with tight tolerances is the
    tracesim bench — this pins the plumbing end to end.)"""
    import jax

    from gym_tpu.models.nanogpt import GPT, GPTConfig
    from gym_tpu.serve.engine import SamplingParams
    from gym_tpu.serve.router import build_fleet
    from gym_tpu.servesim import calibrate_router, replay_router

    cfg = GPTConfig(block_size=64, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True)
    params = GPT(cfg).init({"params": jax.random.PRNGKey(0)},
                           np.zeros((1, 8), np.int64),
                           train=False)["params"]
    m = ServeMetrics(tempfile.mkdtemp(prefix="gym_tpu_svsmoke_"),
                     engine_log_every=10)
    router = build_fleet(params, cfg, replicas=1, num_slots=2,
                         decode_chunk=2, metrics=m,
                         log=lambda *a, **k: None).start()
    try:
        for n in (4, 8, 16):   # warm the buckets the trace hits
            router.submit(np.arange(1, n + 1, dtype=np.int32) % 48,
                          SamplingParams(max_new_tokens=8, seed=n)
                          ).result(timeout=300)
        profile = calibrate_router(router, 48, num_slots=2, probes=1)
        tr = diurnal_trace(duration_s=16, base_rps=1.5, seed=9,
                           prompt_lens=(4, 16), max_news=(8, 16))
        live = replay_router(router, tr, vocab_size=48,
                             time_scale=4.0)["report"]
    finally:
        router.close(drain_deadline_s=60)
        m.close()
    import dataclasses as _dc
    scaled = [_dc.replace(e, arrival_s=e.arrival_s / 4.0) for e in tr]
    model = FleetCostModel(profile, initial_replicas=1,
                           autoscale=False).run(scaled).report()
    assert live["requests"] == model["requests"] == len(tr)
    assert live["done"] == model["done"] == len(tr)
    assert live["shed_rate"] == model["shed_rate"] == 0.0
    # same regime: a feasible trace stays sub-second in both arms
    assert live["ttft_p99_s"] < 1.0, live
    assert model["ttft_p99_s"] < 1.0, model
    assert abs(model["ttft_p99_s"] - live["ttft_p99_s"]) < 0.75


# ---------------------------------------------------------------------------
# the closed-loop drill (in-process flavor; the out-of-process one is
# scripts/ci_deploy.sh)


@pytest.mark.slow
def test_drill_in_process(tmp_path):
    from gym_tpu.servesim.drill import run_drill
    result = run_drill(str(tmp_path / "drill"), replicas=2,
                       out_of_process=False, kill_trainer=False,
                       final_steps=8, trace_duration_s=12.0)
    assert result["ok"], result["failures"]
    assert result["replay"]["done"] == result["replay"]["requests"]
    assert result["post_swap_stream_exact"]
    assert result["compiles_before"] == result["compiles_after"]


# ---------------------------------------------------------------------------
# multi-tenant traces + modeled isolation (ISSUE 17)


def test_tenant_trace_families_deterministic_and_roundtrip(tmp_path):
    """The tenant families are seeded (same seed → identical trace),
    stamp every event with tenant/slo_class, and survive the CSV
    round-trip exactly; a pre-tenant trace file still loads (the
    tolerant-header satellite)."""
    from gym_tpu.servesim.traces import (TRACE_HEADER, load_trace,
                                         make_trace, save_trace,
                                         trace_stats)
    for fam in ("noisy_neighbor", "tenant_flash", "mixed_slo"):
        ev = make_trace(fam, seed=3, duration_s=20.0)
        assert ev == make_trace(fam, seed=3, duration_s=20.0)
        assert all(e.tenant and e.slo_class for e in ev)
        # unique seeds across the merged population: Outcome.index and
        # the per-request sampling keys both key off them
        assert sorted(e.seed for e in ev) == list(range(len(ev)))
        p = str(tmp_path / f"{fam}.csv")
        save_trace(p, ev)
        assert load_trace(p) == ev
        st = trace_stats(ev)
        assert sum(st["tenants"].values()) == len(ev)
    # noisy_neighbor is the headline shape: an interactive victim and
    # a batch flooder
    st = trace_stats(make_trace("noisy_neighbor", seed=0,
                                duration_s=30.0))
    assert set(st["by_class"]) == {"interactive", "batch"}
    # a single-tenant trace still writes (and reloads through) the
    # original 6-column header — old readers keep working
    old = make_trace("diurnal", seed=0, duration_s=10.0)
    p = str(tmp_path / "old.csv")
    save_trace(p, old)
    with open(p) as f:
        assert next(csv.reader(f)) == TRACE_HEADER
    assert load_trace(p) == old


def test_cost_model_isolation_invariant():
    """The modeled twin of the chaos drill: under the noisy-neighbor
    trace, quotas + preemption must STRICTLY improve the interactive
    victim's SLO attainment over no isolation, pay for it in batch
    goodput (quota rejections exist), and stay deterministic."""
    from gym_tpu.servesim.cost_model import class_reports
    from gym_tpu.servesim.traces import make_trace
    prof = ServiceProfile(tokens_per_s=120.0, num_slots=4,
                          max_queue=64, request_overhead_s=0.05)
    events = make_trace("noisy_neighbor", seed=0, duration_s=60.0)

    def run(quotas, preempt):
        res = FleetCostModel(prof, initial_replicas=2,
                             autoscale=False, quotas=quotas,
                             preempt=preempt).run(events)
        return res, class_reports(events, res.outcomes,
                                  slo_ttft_s=2.0)

    res_off, per_off = run(None, False)
    res_on, per_on = run({"batch": {"share": 0.5}}, True)
    att_off = per_off["interactive"]["slo_attainment"]
    att_on = per_on["interactive"]["slo_attainment"]
    assert att_on > att_off
    assert res_on.preemptions >= 1
    assert res_on.quota_rejected.get("batch", 0) > 0
    # isolation off: no tenant machinery fires (single-tenant parity)
    assert res_off.preemptions == 0 and not res_off.quota_rejected
    # determinism: the regression gate depends on it
    res_on2, per_on2 = run({"batch": {"share": 0.5}}, True)
    assert per_on2 == per_on


def test_tenant_gate_record_and_check(tmp_path):
    """The tenant frontier gate's full lifecycle on a scaled-down
    config: record a baseline, re-check clean, then verify a doctored
    baseline (more batch goodput than achievable) trips REGRESSION."""
    import json
    from gym_tpu.servesim.sweep import (TenantSweepConfig,
                                        best_isolation_policy,
                                        run_tenant_cell, tenant_grid)
    from gym_tpu.servesim.tenant_gate import (fast_tenant_frontier,
                                              structural_check)
    cfg = TenantSweepConfig(traces=["noisy_neighbor"],
                            interactive_fracs=[0.5], duration_s=40.0)
    cur = fast_tenant_frontier(cfg)
    assert structural_check(cur)
    assert cur["cells"] == len(tenant_grid(cfg)) == 4
    grp = "noisy_neighbor"
    best = best_isolation_policy(cur["rows"], grp,
                                 cfg.slo_attainment_target)
    assert best is not None, "no policy meets the interactive SLO"
    assert cur["groups"][grp]["policy"] == best["policy"]
    # determinism across runs — the gate's entire premise
    assert fast_tenant_frontier(cfg)["groups"] == cur["groups"]
