"""Fleet serving (ISSUE 8): the multi-replica router — health-aware
dispatch, replica-kill failover, and zero-downtime weight hot-swap.

Acceptance oracles pinned here:

- **failover oracle** — kill one of 2 replicas mid-decode: the affected
  request completes on the sibling inside its original deadline with a
  token stream IDENTICAL to an uncontended ``generate_fast`` run (no
  duplicate tokens, no gaps — partials from the dead attempt are
  discarded, never concatenated); ``Router.status()`` records the
  failover and the dead replica is excluded from dispatch.
- **hot-swap oracle** — roll new params through a 2-replica fleet under
  sustained concurrent traffic: ZERO failed/dropped requests, ZERO
  recompiles (pinned by the device-program registry's build counter),
  and post-swap generations provably come from the NEW params (exact
  ``generate_fast(params_b)`` match).
- **deadline-forwarding satellite** — a failover retry carries the
  request's REMAINING deadline (anchored at the fleet submit entry), so
  a retried request can never wait two full deadlines; a deadline
  already exhausted at failover time surfaces typed, not retried.
- **fleet shutdown drill** — ``create_server(replicas=2)`` torn down
  with in-flight requests on EVERY replica: in-flight answered (200,
  full tokens), queued failed typed (503), a wedged replica gets its
  thread stacks dumped without its engine ever being stepped.
"""

import concurrent.futures
import json
import os
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from gym_tpu.models.nanogpt import GPT, GPTConfig, generate_fast
from gym_tpu.serve.engine import InferenceEngine, SamplingParams
from gym_tpu.serve.load import CheckpointWatcher, latest_checkpoint_step
from gym_tpu.serve.metrics import ServeMetrics, read_headline
from gym_tpu.serve.router import (FleetReloadError, NoHealthyReplicaError,
                                  Router, build_fleet)
from gym_tpu.serve.scheduler import (DeadlineExceededError,
                                     EngineFailedError, RequestStatus,
                                     SchedulerClosedError)
from gym_tpu.utils.resilience import faults


@pytest.fixture(scope="module")
def setup():
    cfg = GPTConfig(block_size=64, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True)
    model = GPT(cfg)
    params_a = model.init({"params": jax.random.PRNGKey(0)},
                          np.zeros((1, 8), np.int64),
                          train=False)["params"]
    params_b = model.init({"params": jax.random.PRNGKey(7)},
                          np.zeros((1, 8), np.int64),
                          train=False)["params"]
    return cfg, params_a, params_b


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _prompt(n, seed, vocab=48):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed), (n,),
                                         0, vocab))


def _fleet(params, cfg, tmp_path=None, *, replicas=2, num_slots=2,
           start=True, **kw):
    m = ServeMetrics(str(tmp_path)) if tmp_path is not None else None
    kw.setdefault("dispatch_timeout_s", 30.0)
    r = build_fleet(params, cfg, replicas=replicas, num_slots=num_slots,
                    metrics=m, log=lambda *a, **k: None, **kw)
    if start:
        r.start()
    return r, m


def _close(router, metrics):
    router.close(drain_deadline_s=30.0)
    if metrics is not None:
        metrics.close()


def _program_misses():
    # the device-program registry's shared build counter (ISSUE 9) —
    # a delta of 0 across an operation is the zero-recompile pin
    from gym_tpu.programs import compile_counter
    return compile_counter()


# -- dispatch -------------------------------------------------------------


def test_dispatch_least_loaded_and_tiebreak(setup):
    """An idle fleet ties to replica 0; a replica carrying backlog loses
    the next pick to its empty sibling."""
    cfg, params, _ = setup
    router, _m = _fleet(params, cfg, start=False)
    a = router.submit(_prompt(5, 0), SamplingParams(max_new_tokens=8),
                      block=False)
    assert a.replica_id == 0              # idle tie → lowest id
    b = router.submit(_prompt(5, 1), SamplingParams(max_new_tokens=8),
                      block=False)
    assert b.replica_id == 1              # replica 0 now carries backlog
    c = router.submit(_prompt(5, 2), SamplingParams(max_new_tokens=2),
                      block=False)
    assert c.replica_id in (0, 1)
    _close(router, None)


def test_dispatch_prefix_affinity_sticks_to_warm_replica(setup):
    """Paged fleets: a prompt whose prefix blocks are resident on one
    replica routes BACK to it — the admit_probe bonus beats the idle
    tie-break that would otherwise send it to replica 0."""
    cfg, params, _ = setup
    router, _m = _fleet(params, cfg, paged=True, page_size=16,
                        kv_pages=64)
    try:
        shared = _prompt(34, 3)           # 2 full pages of shared prefix
        # park a request on replica 0 so the shared prompt lands on 1
        park = router.submit(_prompt(5, 4),
                             SamplingParams(max_new_tokens=24, seed=0))
        assert park.replica_id == 0
        warm = router.submit(shared, SamplingParams(max_new_tokens=4,
                                                    seed=1))
        assert warm.replica_id == 1
        warm.result(timeout=60)
        park.result(timeout=60)
        # both idle again: without the bonus the tie would go to 0 —
        # the resident prefix on 1 must win
        hit = router.submit(shared, SamplingParams(max_new_tokens=4,
                                                   seed=2))
        assert hit.replica_id == 1
        hit.result(timeout=60)
    finally:
        _close(router, None)


# -- failover (the acceptance oracle) -------------------------------------


def test_replica_kill_mid_decode_fails_over_exact_stream(setup, tmp_path):
    """Kill one of 2 replicas mid-decode: the request completes on the
    sibling with the EXACT uncontended token stream (no duplicates, no
    gaps), the failover is recorded, the dead replica leaves dispatch."""
    cfg, params, _ = setup
    router, m = _fleet(params, cfg, tmp_path, max_restarts=0)
    try:
        p = _prompt(6, 10)
        ref = generate_fast(params, cfg, p[None], 24, temperature=0.9,
                            top_k=7, seed=5)[0, 6:].tolist()
        fr = router.submit(p, SamplingParams(max_new_tokens=24,
                                             temperature=0.9, top_k=7,
                                             seed=5), deadline_s=60.0)
        victim = fr.replica_id
        deadline = time.perf_counter() + 30.0
        while len(fr.tokens) < 4:         # mid-decode, provably partial
            assert time.perf_counter() < deadline, "no decode progress"
            time.sleep(0.005)

        def boom(*a, **k):
            raise RuntimeError("test: injected hard engine death")

        router.replicas[victim].scheduler.engine.step = boom
        t0 = time.perf_counter()
        assert fr.result(timeout=60) == ref
        assert time.perf_counter() - t0 < 60.0   # inside the deadline
        assert fr.failovers == 1
        assert fr.replica_id != victim
        # the retry carried the REMAINING deadline, not a fresh one
        assert fr._inner.deadline_s is not None
        assert fr._inner.deadline_s < 60.0
        st = router.status()
        assert st["failovers"] == 1
        assert st["replicas"][victim]["dead"] is True
        assert st["healthy_replicas"] == 1
        # dead replica excluded: every subsequent pick is the sibling
        for i in range(3):
            nxt = router.submit(_prompt(4, 20 + i),
                                SamplingParams(max_new_tokens=2, seed=i))
            assert nxt.replica_id != victim
            assert len(nxt.result(timeout=60)) == 2
    finally:
        _close(router, m)


def test_whole_fleet_dead_degrades_typed(setup):
    """Both replicas broken: the in-flight request exhausts its failover
    budget and surfaces the TYPED engine failure; the next submit draws
    ``NoHealthyReplicaError`` (the HTTP 503), never a bare traceback."""
    cfg, params, _ = setup
    router, _m = _fleet(params, cfg, max_restarts=0)
    try:
        def boom(*a, **k):
            raise RuntimeError("test: injected hard engine death")

        fr = router.submit(_prompt(5, 0),
                           SamplingParams(max_new_tokens=16, seed=0))
        for rep in router.replicas:
            rep.scheduler.engine.step = boom
        # whichever race wins — sibling accepted then died (typed engine
        # failure / closed), or died first (typed 503) — the client gets
        # the fleet's TYPED answer, never a bare traceback
        with pytest.raises((EngineFailedError, SchedulerClosedError,
                            NoHealthyReplicaError)):
            fr.result(timeout=60)
        deadline = time.perf_counter() + 30.0
        while (any(not r.dead for r in router.replicas)
               and time.perf_counter() < deadline):
            time.sleep(0.01)
        with pytest.raises(NoHealthyReplicaError):
            router.submit(_prompt(4, 1), SamplingParams(max_new_tokens=2))
    finally:
        _close(router, None)


def test_dispatch_death_window_is_health_typed_not_queue_full(setup):
    """A replica whose scheduler refuses (closing) BEFORE its supervisor
    sets ``failed`` still counts as alive — a non-blocking dispatch that
    only hit that window must surface the HEALTH signal (typed 503 +
    retry hint), never claim 'queue at capacity'."""
    cfg, params, _ = setup
    router, _m = _fleet(params, cfg, start=False)
    for rep in router.replicas:
        rep.scheduler.shutdown(finish_running=False, deadline_s=0.0)
    assert all(not rep.dead for rep in router.replicas)   # the window
    with pytest.raises(NoHealthyReplicaError,
                       match="shutting down or being declared dead") \
            as ei:
        router.submit(_prompt(4, 0), SamplingParams(max_new_tokens=2),
                      block=False)
    assert ei.value.retry_after_s > 0
    _close(router, None)


def test_failover_deadline_already_exhausted_is_typed(setup):
    """The satellite's hard edge: when the submit-entry-anchored
    deadline has fully elapsed by failover time, the request is NOT
    retried — it fails typed, chained to the replica death."""
    cfg, params, _ = setup
    router, _m = _fleet(params, cfg, start=False)
    fr = router.submit(_prompt(5, 0), SamplingParams(max_new_tokens=4),
                       deadline_s=5.0, block=False)
    fr.submit_t -= 10.0                   # elapsed > deadline_s
    router.replicas[fr.replica_id].scheduler.shutdown(
        finish_running=False, deadline_s=0.0)
    with pytest.raises(DeadlineExceededError,
                       match="during replica failover"):
        fr.result(timeout=5)
    assert fr.failovers == 0
    _close(router, None)


def test_failover_forwards_remaining_deadline(setup):
    """A queued request whose replica dies is re-dispatched with
    ``deadline_s`` minus the time already spent — never a fresh clock."""
    cfg, params, _ = setup
    router, _m = _fleet(params, cfg, start=False)
    fr = router.submit(_prompt(5, 0), SamplingParams(max_new_tokens=4),
                       deadline_s=30.0, block=False)
    first = fr.replica_id
    fr.submit_t -= 3.0                    # 3 s already "spent"
    router.replicas[first].scheduler.shutdown(finish_running=False,
                                              deadline_s=0.0)
    with pytest.raises(TimeoutError):     # re-queued on the sibling,
        fr.result(timeout=0.05)           # which is not running — fine
    assert fr.failovers == 1
    assert fr.replica_id != first
    assert fr._inner.deadline_s == pytest.approx(27.0, abs=1.0)
    _close(router, None)


# -- zero-downtime weight hot-swap (the acceptance oracle) ----------------


def test_rolling_hot_swap_under_traffic(setup, tmp_path):
    """Swap weights across the fleet under sustained concurrent traffic:
    zero failed requests, zero recompiles (registry builds pinned),
    and a post-swap generation that matches ``generate_fast`` under the
    NEW params exactly."""
    cfg, params_a, params_b = setup
    router, m = _fleet(params_a, cfg, tmp_path, weights_tag="v1",
                       max_restarts=2)
    try:
        probe = _prompt(6, 30)
        ref_b = generate_fast(params_b, cfg, probe[None], 8,
                              temperature=0.9, top_k=7,
                              seed=9)[0, 6:].tolist()
        # warm every program before the pinned window: the clients below
        # send prompts of 4..8 tokens, i.e. BOTH the 4- and 8-token
        # prefill buckets (the shared registry means one warm request
        # per bucket covers both replicas)
        router.submit(probe, SamplingParams(max_new_tokens=2,
                                            seed=0)).result(timeout=60)
        router.submit(_prompt(4, 31), SamplingParams(
            max_new_tokens=2, seed=0)).result(timeout=60)
        misses0 = _program_misses()

        def client(i):
            fr = router.submit(
                _prompt(4 + i % 5, 40 + i),
                SamplingParams(max_new_tokens=10, seed=i), timeout=60.0)
            return len(fr.result(timeout=120)) == 10

        reload_result = {}

        def do_reload():
            time.sleep(0.1)               # let traffic occupy the fleet
            reload_result.update(router.reload(params_b,
                                               weights_tag="v2",
                                               drain_timeout_s=60.0))

        swapper = threading.Thread(target=do_reload)
        with concurrent.futures.ThreadPoolExecutor(4) as ex:
            futs = [ex.submit(client, i) for i in range(12)]
            swapper.start()
            results = [f.result() for f in futs]
        swapper.join(timeout=60)
        assert not swapper.is_alive()
        assert all(results), f"hot-swap dropped {results.count(False)}"
        assert sorted(reload_result["swapped"]) == [0, 1]
        assert reload_result["skipped"] == []
        assert _program_misses() == misses0      # zero recompiles
        # post-swap generations provably come from the NEW params
        fr = router.submit(probe, SamplingParams(
            max_new_tokens=8, temperature=0.9, top_k=7, seed=9))
        assert fr.result(timeout=60) == ref_b
        st = router.status()
        assert st["weight_reloads"] == 1
        assert st["weights_tag"] == "v2"
        assert all(r["weights_tag"] == "v2" for r in st["replicas"])
        # the collector's engine_reloads counts per-ENGINE swap events
        # (like engine_restarts): one rollout × two replicas — distinct
        # from the router's rollout-count weight_reloads above
        head = m.headline()
        assert head["engine_reloads"] == 2
        assert all(head["replicas"][rid]["engine_reloads"] == 1
                   for rid in ("0", "1"))
    finally:
        _close(router, m)


def test_replace_engine_bumps_epoch_against_stale_admit(setup):
    """The hot-swap race pin: a driver iteration that snapshotted
    (epoch, engine) BEFORE the swap must not admit a queued request
    into the detached old engine — ``replace_engine`` bumps the epoch,
    so the stale ``_admit_from_queue`` is a no-op and the request
    admits onto the NEW engine instead."""
    cfg, params, _ = setup
    from gym_tpu.serve.scheduler import Scheduler
    old = InferenceEngine(params, cfg, num_slots=2)
    sched = Scheduler(old, max_queue=4)
    h = sched.submit(_prompt(5, 0), SamplingParams(max_new_tokens=3,
                                                   seed=1))
    stale_epoch = sched._epoch
    sched.replace_engine(InferenceEngine(params, cfg, num_slots=2))
    assert sched._admit_from_queue(stale_epoch, old) == 0
    assert h.status is RequestStatus.QUEUED   # still queued, not lost
    assert old.stats.prefills == 0            # old engine never touched
    while h.status in (RequestStatus.QUEUED, RequestStatus.RUNNING):
        sched.step()                          # admits onto the NEW engine
    assert len(h.result(timeout=1)) == 3
    assert sched.engine.stats.prefills == 1


def test_reload_drain_timeout_is_transient_typed(setup):
    """A replica that cannot drain inside the bound aborts the rollout
    with a RETRYABLE typed error (``retry_after_s`` set → HTTP 503),
    distinct from the reload-already-rolling conflict (409)."""
    cfg, params_a, params_b = setup
    router, _m = _fleet(params_a, cfg, num_slots=1)
    try:
        faults.install("serve.decode", "delay", arg=0.05)
        fr = router.submit(_prompt(5, 0),
                           SamplingParams(max_new_tokens=40, seed=0))
        deadline = time.perf_counter() + 30.0
        while router.replicas[fr.replica_id].scheduler.inflight() == 0:
            assert time.perf_counter() < deadline, "never admitted"
            time.sleep(0.005)
        with pytest.raises(FleetReloadError, match="did not drain") as ei:
            router.reload(params_b, weights_tag="v2",
                          drain_timeout_s=0.01)
        assert ei.value.retry_after_s is not None   # transient → 503
        faults.reset()
        assert len(fr.result(timeout=60)) == 40     # request unharmed
        # the aborted rollout released the serialization flag
        res = router.reload(params_b, weights_tag="v2",
                            drain_timeout_s=60.0)
        assert sorted(res["swapped"]) == [0, 1]
    finally:
        _close(router, None)


def test_reload_skips_dead_replica_and_serializes(setup):
    """A dead replica is skipped (its eventual rebuild reads the updated
    params box anyway); a second concurrent reload is refused typed."""
    cfg, params_a, params_b = setup
    router, _m = _fleet(params_a, cfg, start=False, weights_tag="v1")
    router.replicas[0].supervisor.failed = RuntimeError("test: dead")
    res = router.reload(params_b, weights_tag="v2")
    assert res["swapped"] == [1] and res["skipped"] == [0]
    assert router.params_box["params"] is params_b
    assert router.replicas[1].scheduler.engine.weights_tag == "v2"
    router._reloading = True              # a rollout mid-flight
    with pytest.raises(FleetReloadError, match="already in progress"):
        router.reload(params_b, weights_tag="v3")
    router._reloading = False
    _close(router, None)


# -- fleet shutdown (satellite drill) -------------------------------------


def test_fleet_shutdown_inflight_answered_queued_typed(setup, tmp_path):
    """``create_server(replicas=2)`` torn down with a running request on
    EVERY replica and more queued behind them: the running ones are
    answered 200 with full tokens (one per replica — the fleet really
    was draining both), the queued ones fail typed 503."""
    cfg, params, _ = setup
    from gym_tpu.serve.__main__ import create_server
    handle = create_server(params, cfg, port=0, num_slots=1, replicas=2,
                           metrics_dir=str(tmp_path),
                           dispatch_timeout=30.0, request_timeout=120.0)
    t = threading.Thread(target=handle.httpd.serve_forever, daemon=True)
    t.start()
    try:
        faults.install("serve.decode", "delay", arg=0.05)

        def post(i):
            body = json.dumps({"prompt": [1, 2, 3 + i],
                               "max_new_tokens": 12, "seed": i}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{handle.port}/generate", body,
                {"Content-Type": "application/json"})
            try:
                r = urllib.request.urlopen(req, timeout=120)
                return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        with concurrent.futures.ThreadPoolExecutor(4) as ex:
            futs = [ex.submit(post, i) for i in range(4)]
            # both single-slot replicas running, the rest queued
            deadline = time.perf_counter() + 30.0
            while (sum(r.scheduler.active_requests()
                       for r in handle.router.replicas) < 2):
                assert time.perf_counter() < deadline, "slots never filled"
                time.sleep(0.01)
            # close drains replicas SEQUENTIALLY: freeze admission
            # fleet-wide first so a queued request cannot slip into a
            # slot replica 1 frees while replica 0 is still draining —
            # the drill pins "running answered, queued failed", not the
            # race of which queued request got lucky
            for rep in handle.router.replicas:
                rep.scheduler.pause_admission()
            handle.close(drain_deadline_s=60.0)
            results = [f.result() for f in futs]
        oks = [(c, b) for c, b in results if c == 200]
        fails = [(c, b) for c, b in results if c != 200]
        assert len(oks) == 2 and len(fails) == 2, results
        assert all(len(b["tokens"]) == 12 for _, b in oks)
        assert {b["replica"] for _, b in oks} == {0, 1}
        for code, body in fails:
            assert code == 503
            assert "shutting down" in body["error"]
    finally:
        faults.reset()
        t.join(timeout=10)


def test_fleet_close_dumps_stacks_for_wedged_replica(setup, capsys):
    """A replica whose driver never exits the drain gets its thread
    stacks dumped (per-replica evidence) and its requests failed typed
    WITHOUT its engine being stepped; siblings still drain clean."""
    cfg, params, _ = setup
    router, _m = _fleet(params, cfg, start=False)
    router.replicas[0].supervisor.stop = lambda **k: False
    q = router.submit(_prompt(4, 0), SamplingParams(max_new_tokens=4),
                      block=False)
    assert q.replica_id == 0
    assert router.close(drain_deadline_s=0.5) is False
    assert "replica 0 driver wedged" in capsys.readouterr().err
    assert q.status is RequestStatus.FAILED
    with pytest.raises(SchedulerClosedError):
        q.result(timeout=1)


# -- HTTP fleet surface ----------------------------------------------------


def test_http_fleet_stats_and_reload(setup, tmp_path):
    """The wire-level fleet story: /generate reports its replica,
    /stats carries the per-replica section, POST /reload hot-swaps the
    weights and the very next generation comes from the new params."""
    cfg, params_a, params_b = setup
    from gym_tpu.serve.__main__ import create_server
    handle = create_server(
        params_a, cfg, port=0, num_slots=2, replicas=2,
        metrics_dir=str(tmp_path), dispatch_timeout=30.0,
        request_timeout=120.0,
        reload_source=lambda body: (params_b,
                                    body.get("tag", "step-9")))
    t = threading.Thread(target=handle.httpd.serve_forever, daemon=True)
    t.start()

    def post(path, payload):
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{handle.port}{path}", body,
            {"Content-Type": "application/json"})
        try:
            r = urllib.request.urlopen(req, timeout=120)
            return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        ref_b = generate_fast(params_b, cfg,
                              np.asarray([[1, 2, 3]]), 6,
                              temperature=1.0, top_k=4,
                              seed=0)[0, 3:].tolist()
        code, body = post("/generate", {"prompt": [1, 2, 3],
                                        "max_new_tokens": 6,
                                        "top_k": 4, "seed": 0})
        assert code == 200 and len(body["tokens"]) == 6
        assert body["replica"] in (0, 1) and body["failovers"] == 0
        assert body["tokens"] != ref_b    # still the old params
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{handle.port}/stats", timeout=30).read())
        assert stats["healthy_replicas"] == 2
        assert stats["failovers"] == 0 and stats["weight_reloads"] == 0
        assert [r["id"] for r in stats["replicas"]] == [0, 1]
        assert all(r["healthy"] for r in stats["replicas"])
        code, body = post("/reload", {"tag": "step-9"})
        assert code == 200, body
        assert sorted(body["swapped"]) == [0, 1]
        assert body["weights_tag"] == "step-9"
        code, body = post("/generate", {"prompt": [1, 2, 3],
                                        "max_new_tokens": 6,
                                        "top_k": 4, "seed": 0})
        assert code == 200 and body["tokens"] == ref_b
        stats = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{handle.port}/stats", timeout=30).read())
        assert stats["weight_reloads"] == 1
        assert stats["weights_tag"] == "step-9"
        assert stats["step"] == 9         # "step" tracks the live weights
    finally:
        handle.close()
        t.join(timeout=10)


def test_http_reload_bad_bodies_are_400_typed(setup, tmp_path):
    """Every malformed /reload body — no source configured, non-object
    JSON, non-numeric drain_timeout_s — draws a typed 400 JSON reply,
    never a handler traceback with a dropped connection."""
    cfg, params, params_b = setup
    from gym_tpu.serve.__main__ import create_server
    handle = create_server(params, cfg, port=0, num_slots=1,
                           metrics_dir=str(tmp_path),
                           dispatch_timeout=30.0)
    t = threading.Thread(target=handle.httpd.serve_forever, daemon=True)
    t.start()

    def post_reload(raw):
        req = urllib.request.Request(
            f"http://127.0.0.1:{handle.port}/reload", raw,
            {"Content-Type": "application/json"})
        try:
            r = urllib.request.urlopen(req, timeout=30)
            return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    try:
        code, body = post_reload(b"{}")
        assert code == 400 and "no reload source" in body["error"]
    finally:
        handle.close()
        t.join(timeout=10)
    handle = create_server(
        params, cfg, port=0, num_slots=1,
        metrics_dir=str(tmp_path / "b"), dispatch_timeout=30.0,
        reload_source=lambda body: (params_b, "v2"))
    t = threading.Thread(target=handle.httpd.serve_forever, daemon=True)
    t.start()
    try:
        for raw in (b"[1, 2]",
                    json.dumps({"drain_timeout_s": "fast"}).encode(),
                    json.dumps({"drain_timeout_s": [1]}).encode()):
            code, body = post_reload(raw)
            assert code == 400, (raw, code, body)
            assert "malformed reload body" in body["error"], body
        code, body = post_reload(b"{}")   # a good body still works
        assert code == 200 and sorted(body["swapped"]) == [0]
    finally:
        handle.close()
        t.join(timeout=10)


# -- per-replica metrics (satellite) --------------------------------------


def _fake_req(rid, tokens, ttft, lat, exc=None):
    return types.SimpleNamespace(
        id=rid, prompt=np.zeros(4, np.int32), tokens=list(range(tokens)),
        error=None if exc is None else str(exc), exception=exc,
        ttft_s=ttft, avg_token_latency_s=lat)


def test_metrics_replica_views_isolate_ewma_and_counters(tmp_path):
    """Two replicas' interleaved engine ticks must never be differenced
    against each other: each view keeps its own EWMA, and the headline
    grows a per-replica section plus fleet-aggregate rates."""
    m = ServeMetrics(str(tmp_path), engine_log_every=1)
    v0, v1 = m.replica_view(0), m.replica_view(1)
    s0 = types.SimpleNamespace(tokens_generated=0, active_slots=1)
    s1 = types.SimpleNamespace(tokens_generated=0, active_slots=1)
    v0.engine_tick(s0, queue_depth=0)
    v1.engine_tick(s1, queue_depth=0)
    time.sleep(0.02)
    s0.tokens_generated, s1.tokens_generated = 100, 10
    v0.engine_tick(s0, queue_depth=0)     # interleaved, per-replica safe
    v1.engine_tick(s1, queue_depth=0)
    e0, e1 = v0.tokens_per_s_ewma(), v1.tokens_per_s_ewma()
    assert e0 is not None and e1 is not None and e0 > e1
    assert m.tokens_per_s_ewma() == pytest.approx(e0 + e1)
    v0.request_done(_fake_req(1, 5, 0.1, 0.01), queue_depth=0,
                    active_slots=1)
    v1.request_done(_fake_req(2, 3, 0.1, 0.01,
                              exc=DeadlineExceededError("late")),
                    queue_depth=0, active_slots=1)
    v0.engine_restarted()
    v1.engine_reloaded()
    head = m.headline()
    assert head["requests_done"] == 1 and head["requests_failed"] == 1
    assert head["engine_restarts"] == 1 and head["engine_reloads"] == 1
    reps = head["replicas"]
    assert reps["0"]["requests_done"] == 1
    assert reps["0"]["engine_restarts"] == 1
    assert reps["1"]["requests_failed"] == 1
    assert reps["1"]["engine_reloads"] == 1
    assert reps["0"]["tokens_per_s_ewma"] > reps["1"]["tokens_per_s_ewma"]
    m.close()
    # the CSV round-trips the same per-replica story
    head2 = read_headline(os.path.join(str(tmp_path), "serve.csv"))
    assert head2["requests_done"] == 1
    assert head2["engine_restarts"] == 1
    assert head2["engine_reloads"] == 1
    assert head2["replicas"]["0"]["requests_done"] == 1
    assert head2["replicas"]["1"]["engine_reloads"] == 1


def test_read_headline_tolerates_pre_fleet_csv(tmp_path):
    """A pre-fleet CSV (no ``replica_id`` column — like pre-paging CSVs
    lack the KV columns) still aggregates, with NO replicas section."""
    path = tmp_path / "serve.csv"
    rows = ["ts_s,kind,request_id,status,queue_depth,active_slots,"
            "prompt_tokens,new_tokens,ttft_s,avg_token_latency_s,"
            "cum_tokens,tokens_per_s",
            "0.5,request,1,done,0,1,4,3,0.10000,0.01000,3,1.0",
            "0.9,engine,,restart,,,,,,,3,1.0"]
    path.write_text("\n".join(rows) + "\n")
    head = read_headline(str(path))
    assert head["requests_done"] == 1
    assert head["engine_restarts"] == 1
    assert head["engine_reloads"] == 0
    assert "replicas" not in head


# -- checkpoint watcher (hot-swap push half) ------------------------------


def test_checkpoint_watcher_fires_only_on_newer_committed(tmp_path):
    """Committed = the dir name is a bare integer (Orbax renames on
    commit; quarantined dirs carry a suffix). Only strictly newer steps
    fire, and a failing callback must not kill the watcher."""
    run = tmp_path / "run"
    run.mkdir()
    assert latest_checkpoint_step(str(run)) is None
    (run / "100").mkdir()
    (run / "150.corrupt-1").mkdir()
    (run / "200.tmp-orbax").mkdir()
    assert latest_checkpoint_step(str(run)) == 100
    fired = []
    w = CheckpointWatcher(str(run), fired.append, poll_s=3600.0,
                          initial_step=100)
    assert w.poll_once() is None          # nothing newer than 100
    (run / "200").mkdir()
    assert w.poll_once() == 200
    assert w.poll_once() is None          # 200 already seen
    assert fired == [200]

    def explode(step):
        fired.append(step)
        raise RuntimeError("test: reload blew up")

    w2 = CheckpointWatcher(str(run), explode, poll_s=3600.0,
                           initial_step=100)
    assert w2.poll_once() == 200          # callback error swallowed
    (run / "300").mkdir()
    assert w2.poll_once() == 300          # watcher survived, fired again
    assert fired == [200, 200, 300]


def test_checkpoint_watcher_drives_router_reload(setup, tmp_path):
    """End to end: a trainer committing a newer checkpoint dir rolls the
    new weights through the fleet via the watcher callback."""
    cfg, params_a, params_b = setup
    router, _m = _fleet(params_a, cfg, start=False, weights_tag="step-1")
    run = tmp_path / "run"
    run.mkdir()
    (run / "1").mkdir()

    def on_new_step(step):
        router.reload(params_b, weights_tag=f"step-{step}")

    w = CheckpointWatcher(str(run), on_new_step, poll_s=3600.0,
                          initial_step=1)
    assert w.poll_once() is None
    (run / "2").mkdir()
    assert w.poll_once() == 2
    st = router.status()
    assert st["weights_tag"] == "step-2"
    assert st["weight_reloads"] == 1
    _close(router, None)
