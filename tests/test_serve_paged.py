"""Paged prefix-shared KV cache + speculative decoding (ISSUE 7).

Oracles:
- TOKEN EXACTNESS: the paged engine (speculation off) emits tokens
  IDENTICAL to ``generate_fast`` for the same seed/sampling — including
  padded-bucket prompts, prompts served THROUGH shared prefix blocks,
  the copy-on-write full-hit path, and a real restored checkpoint. The
  paged attend runs the same static-[block_size] reductions and masks
  as the unpaged one, so the streams match bitwise.
- SPECULATIVE EXACTNESS: the speculative engine equals the
  non-speculative engine token-for-token — pinned greedy (the ISSUE 7
  acceptance bar) AND under full sampling (the deterministic-draft
  scheme samples every position from the true conditional with the
  request's own key schedule, so drafts only decide how many samples a
  dispatch keeps).
- BOUNDED COMPILATION: paged prefill stays under the
  ``⌈log2(block_size)⌉ + 1`` bucket bound; decode/draft-verify are one
  program each.
- ALLOCATOR: refcounts, LRU eviction of refcount-0 cached blocks,
  double-free detection, pool-exhaustion requeue (requests wait, never
  fail), and release returning every non-cached block.
"""

import os

import numpy as np
import pytest

import jax

from gym_tpu.models.nanogpt import GPT, GPTConfig, generate_fast
from gym_tpu.serve.engine import (BlockAllocator, InferenceEngine,
                                  NoFreeBlocksError, SamplingParams,
                                  max_prefill_buckets)
from gym_tpu.serve.metrics import ServeMetrics, read_headline
from gym_tpu.serve.scheduler import RequestStatus, Scheduler


@pytest.fixture(scope="module")
def setup():
    cfg = GPTConfig(block_size=64, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True)
    model = GPT(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int64), train=False)["params"]
    return cfg, model, params


def _prompt(n, seed, vocab=48):
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed), (n,),
                                         0, vocab))


def _run_one(eng, prompt, sp):
    """Admit one request and drain it; returns its token stream."""
    slot, ev = eng.admit(prompt, sp)
    toks = [ev.token]
    while not ev.finished:
        evs = [e for e in eng.step() if e.slot == slot]
        toks.extend(e.token for e in evs)
        ev = evs[-1]
    return toks


def _drain(sched, handles, limit=5000):
    for _ in range(limit):
        if all(h.status in (RequestStatus.DONE, RequestStatus.FAILED)
               for h in handles):
            return
        sched.step()
    raise AssertionError("scheduler did not drain")


# -- paged-engine token exactness ------------------------------------------


@pytest.mark.parametrize("plen,mnew,kw", [
    (8, 10, dict(temperature=0.8, top_k=5, seed=3)),
    (11, 7, dict(top_p=0.9, seed=5)),          # padded prefill bucket
    (16, 5, dict(top_k=1, seed=2)),            # greedy, block-aligned
])
def test_paged_matches_generate_fast(setup, plen, mnew, kw):
    cfg, model, params = setup
    prompt = _prompt(plen, plen)
    ref = generate_fast(params, cfg, prompt[None], mnew, **kw)
    eng = InferenceEngine(params, cfg, num_slots=2, paged=True,
                          page_size=8)
    got = _run_one(eng, prompt, SamplingParams(max_new_tokens=mnew, **kw))
    assert got == ref[0, plen:].tolist()


def test_prefix_sharing_admits_without_reprefill_and_stays_exact(setup):
    """Two prompts sharing a 24-token prefix (3 pages of 8): the second
    admit reuses the resident blocks (prefix_hit_blocks ticks, prefill
    shrinks to the suffix bucket) and BOTH streams equal their solo
    generate_fast runs — sharing is copy-free AND bit-exact."""
    cfg, model, params = setup
    shared = _prompt(24, 70)
    pa = np.concatenate([shared, _prompt(4, 71)])
    pb = np.concatenate([shared, _prompt(4, 72)])
    eng = InferenceEngine(params, cfg, num_slots=2, paged=True,
                          page_size=8)
    ra = _run_one(eng, pa, SamplingParams(max_new_tokens=6,
                                          temperature=0.8, top_k=5,
                                          seed=1))
    assert eng.stats.prefix_hit_blocks == 0      # cold cache: no hits yet
    tokens_first = eng.stats.prefill_tokens
    rb = _run_one(eng, pb, SamplingParams(max_new_tokens=6,
                                          temperature=0.8, top_k=5,
                                          seed=2))
    assert eng.stats.prefix_hit_blocks == 3
    # 28-token prompt, 24 shared -> only the 4-token suffix (bucket 4)
    # is prefilled; the PR-4 engine would redo all 28 (bucket 32)
    assert eng.stats.prefill_tokens - tokens_first == 4
    assert ra == generate_fast(params, cfg, pa[None], 6, temperature=0.8,
                               top_k=5, seed=1)[0, 28:].tolist()
    assert rb == generate_fast(params, cfg, pb[None], 6, temperature=0.8,
                               top_k=5, seed=2)[0, 28:].tolist()


def test_full_block_aligned_hit_takes_cow_path(setup):
    """A fully block-aligned resident prompt re-admits through
    copy-on-write: one page copy + a 1-token prefill (the last prompt
    token is re-forwarded for the first-token logits), and the stream
    stays exact. The shared source page is NOT perturbed: a third
    request over the same prefix is exact too."""
    cfg, model, params = setup
    p16 = _prompt(16, 80)
    eng = InferenceEngine(params, cfg, num_slots=2, paged=True,
                          page_size=8)
    r1 = _run_one(eng, p16, SamplingParams(max_new_tokens=5, top_k=4,
                                           seed=3))
    before = eng.stats.prefill_tokens
    r2 = _run_one(eng, p16, SamplingParams(max_new_tokens=5, top_k=4,
                                           seed=4))
    assert eng.stats.prefill_tokens - before == 1     # CoW: 1-token bucket
    assert eng.stats.prefix_hit_blocks == 2           # 1 shared + 1 CoW'd
    r3 = _run_one(eng, p16, SamplingParams(max_new_tokens=5, top_k=4,
                                           seed=3))
    for r, seed in ((r1, 3), (r2, 4), (r3, 3)):
        assert r == generate_fast(params, cfg, p16[None], 5, top_k=4,
                                  seed=seed)[0, 16:].tolist()


def test_paged_concurrent_churn_isolated_and_blocks_freed(setup):
    """5 mixed requests through 2 slots over ONE shared pool: every
    stream equals its solo generate_fast run (pages cannot leak across
    slots) and the pool drains back to zero live blocks."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2, decode_chunk=4,
                          paged=True, page_size=8)
    sched = Scheduler(eng, max_queue=8)
    handles, wants = [], []
    for i, (plen, mnew) in enumerate([(5, 7), (9, 12), (3, 4), (17, 9),
                                      (8, 15)]):
        prompt = _prompt(plen, 100 + i)
        ref = generate_fast(params, cfg, prompt[None], mnew,
                            temperature=0.9, top_k=7, top_p=0.95, seed=i)
        wants.append(ref[0, plen:].tolist())
        handles.append(sched.submit(prompt, SamplingParams(
            max_new_tokens=mnew, temperature=0.9, top_k=7, top_p=0.95,
            seed=i)))
    _drain(sched, handles)
    for h, want in zip(handles, wants):
        assert h.result(timeout=1) == want
    assert eng.stats.kv_blocks_in_use == 0


def test_paged_restored_checkpoint_serves_exactly(setup, tmp_path):
    """The paged oracle holds on a REAL restored checkpoint, not just
    hand-built params (ISSUE 7 acceptance)."""
    from gym_tpu import Trainer
    from gym_tpu.data import ArrayDataset
    from gym_tpu.serve.load import load_for_serving
    from gym_tpu.strategy.optim import OptimSpec
    from gym_tpu.strategy.simple_reduce import SimpleReduceStrategy

    cfg = GPTConfig(block_size=32, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 48, (64, 33))
    ds = ArrayDataset(toks[:, :-1].astype(np.int64),
                      toks[:, 1:].astype(np.int64))
    Trainer(GPT(cfg), ds).fit(
        strategy=SimpleReduceStrategy(optim_spec=OptimSpec("adamw",
                                                           lr=1e-3)),
        num_nodes=1, max_steps=4, batch_size=4, val_size=0,
        val_interval=0, show_progress=False, seed=1,
        checkpoint_interval=4, save_dir=str(tmp_path / "ckpts"),
        run_name="paged", log_dir=str(tmp_path / "logs"))
    params, lcfg, _ = load_for_serving(str(tmp_path / "ckpts" / "paged"))
    prompt = _prompt(9, 4, vocab=lcfg.vocab_size)
    ref = generate_fast(params, lcfg, prompt[None], 8, temperature=0.7,
                        top_k=8, seed=2)
    eng = InferenceEngine(params, lcfg, num_slots=2, paged=True,
                          page_size=8)
    got = _run_one(eng, prompt, SamplingParams(max_new_tokens=8,
                                               temperature=0.7, top_k=8,
                                               seed=2))
    assert got == ref[0, 9:].tolist()


def test_paged_teacher_forcing_logits_match_dense_forward(setup):
    """override_tokens still forces a chunk-1 program on the paged
    engine; per-step logits equal the dense forward."""
    cfg, model, params = setup
    seq = _prompt(12, 9)[None]
    full = np.asarray(model.apply({"params": params}, seq, train=False))
    eng = InferenceEngine(params, cfg, num_slots=2, decode_chunk=4,
                          paged=True, page_size=8)
    slot, _ = eng.admit(seq[0, :5], SamplingParams(max_new_tokens=12))
    eng.step(override_tokens={slot: int(seq[0, 5])})
    np.testing.assert_allclose(eng.last_logits[slot], full[0, 5],
                               rtol=1e-4, atol=1e-5)


# -- speculative decoding --------------------------------------------------


def test_speculative_greedy_exact_vs_nonspeculative(setup):
    """The ISSUE 7 pinned oracle: speculative greedy == non-speculative
    greedy == generate_fast greedy."""
    cfg, model, params = setup
    prompt = _prompt(9, 13)
    ref = generate_fast(params, cfg, prompt[None], 14, top_k=1,
                        seed=6)[0, 9:].tolist()
    plain = InferenceEngine(params, cfg, num_slots=2, paged=True,
                            page_size=8, decode_chunk=2)
    spec = InferenceEngine(params, cfg, num_slots=2, paged=True,
                           page_size=8, decode_chunk=2, spec_tokens=4)
    sp = SamplingParams(max_new_tokens=14, top_k=1, seed=6)
    got_plain = _run_one(plain, prompt, sp)
    got_spec = _run_one(spec, prompt, sp)
    assert got_plain == ref
    assert got_spec == ref
    # greedy self-drafting on a tiny model actually accepts drafts —
    # the speedup lever is real, not vacuously exact
    assert spec.stats.spec_drafted > 0
    assert spec.stats.spec_accepted > 0
    assert spec.stats.spec_accept_rate() > 0


@pytest.mark.parametrize("kw", [
    dict(temperature=0.9, top_k=7, seed=5),
    dict(temperature=1.1, top_p=0.9, seed=8),
])
def test_speculative_sampling_exact_vs_nonspeculative(setup, kw):
    """Stronger than the acceptance bar: the deterministic-draft scheme
    is exact for EVERY sampling configuration (each position is sampled
    from the true conditional with the request's own fold_in key), not
    just greedy."""
    cfg, model, params = setup
    prompt = _prompt(10, 21)
    ref = generate_fast(params, cfg, prompt[None], 12,
                        **kw)[0, 10:].tolist()
    spec = InferenceEngine(params, cfg, num_slots=2, paged=True,
                           page_size=8, decode_chunk=3, spec_tokens=3)
    got = _run_one(spec, prompt, SamplingParams(max_new_tokens=12, **kw))
    assert got == ref


def test_speculative_eos_mid_chunk(setup):
    """EOS inside an accepted draft run stops the request at the EOS
    token (inclusive), exactly like non-speculative decoding."""
    cfg, model, params = setup
    prompt = _prompt(9, 3)
    ref = generate_fast(params, cfg, prompt[None], 12, temperature=0.9,
                        top_k=7, seed=1)[0, 9:].tolist()
    eos = ref[4]
    assert eos not in ref[:4]
    eng = InferenceEngine(params, cfg, num_slots=2, paged=True,
                          page_size=8, decode_chunk=4, spec_tokens=3)
    got = _run_one(eng, prompt, SamplingParams(
        max_new_tokens=12, temperature=0.9, top_k=7, seed=1,
        eos_token=eos))
    assert got == ref[:5]


def test_speculative_requires_paged(setup):
    cfg, model, params = setup
    with pytest.raises(ValueError, match="paged"):
        InferenceEngine(params, cfg, num_slots=2, spec_tokens=2)


# -- bounded compilation ---------------------------------------------------


def test_paged_prefill_compile_bound(setup):
    """32 distinct prompt lengths through the paged engine compile at
    most ⌈log2(block_size)⌉ + 1 prefill programs; decode and the fused
    draft/verify are one program each (registry keys cover
    (config, slots, chunk[, γ]) only)."""
    cfg, model, params = setup
    from gym_tpu.programs import compile_counter, default_registry
    eng = InferenceEngine(params, cfg, num_slots=2, paged=True,
                          page_size=8)
    sched = Scheduler(eng, max_queue=64)
    handles = [sched.submit(_prompt(n, 200 + n),
                            SamplingParams(max_new_tokens=2, seed=n))
               for n in range(1, 33)]
    _drain(sched, handles)
    for h in handles:
        assert len(h.result(timeout=1)) == 2
    bound = max_prefill_buckets(cfg.block_size)
    assert eng.stats.prefill_compiles <= bound
    assert len(eng.stats.prefill_buckets) <= bound
    # one decode program per (config, slots, chunk); one spec program
    # per (config, slots, chunk, γ) — engines over the same config
    # resolve to the SAME registry entry (same key, zero new builds)
    builds0 = compile_counter()
    eng2 = InferenceEngine(params, cfg, num_slots=2, paged=True,
                           page_size=8)
    assert eng2._decode_prog.key_hash == eng._decode_prog.key_hash
    s1 = InferenceEngine(params, cfg, num_slots=2, paged=True,
                         page_size=8, spec_tokens=3)
    s2 = InferenceEngine(params, cfg, num_slots=2, paged=True,
                         page_size=8, spec_tokens=3)
    assert s1._spec_prog.key_hash == s2._spec_prog.key_hash
    assert compile_counter() == builds0   # re-acquisition compiles nothing
    names = set(default_registry().keys().values())
    assert any(n.startswith("serve.paged_decode[") for n in names)
    assert any(n.startswith("serve.spec_decode[") for n in names)


# -- allocator semantics ---------------------------------------------------


def test_allocator_refcount_and_free_list():
    al = BlockAllocator(num_pages=5, page_size=4)
    a, b = al.alloc(), al.alloc()
    assert a != b and 0 not in (a, b)
    assert al.in_use() == 2 and al.available() == 2
    al.incref(a)
    al.decref(a)
    assert al.in_use() == 2                  # still referenced once
    al.decref(a)
    assert al.in_use() == 1 and al.available() == 3
    with pytest.raises(ValueError, match="double-freed"):
        al.decref(a)
    al.decref(b)
    assert al.available() == 4


def test_allocator_prefix_cache_lru_eviction():
    """Cached refcount-0 blocks stay resident and are evicted LRU when
    the free list runs dry; a resident block's chain survives a child
    eviction but a parent eviction orphans (and never falsely serves)
    its children."""
    al = BlockAllocator(num_pages=4, page_size=2)      # 3 real pages
    blk = lambda s: s.encode()  # noqa: E731
    p1 = al.alloc()
    c1 = al.register(0, blk("aa"), p1)
    p2 = al.alloc()
    c2 = al.register(c1, blk("bb"), p2)
    al.decref(p1)
    al.decref(p2)
    assert al.cached() == 2 and al.available() == 3
    assert al.lookup(0, blk("aa"))[0] == p1
    assert al.lookup(c1, blk("bb"))[0] == p2
    # exhaust the pool: the third page comes from the free list, the
    # fourth evicts the LRU cached page — "aa" was refreshed by the
    # lookup above, so "bb"... was too (later); evict order follows
    # recency: "aa" then "bb"
    p3 = al.alloc()
    p4 = al.alloc()
    assert {p3, p4} & {p1, p2}               # reused a cached page
    assert al.cached() == 1
    p5 = al.alloc()                          # evicts the last cached page
    assert al.cached() == 0
    with pytest.raises(NoFreeBlocksError):
        al.alloc()                           # everything referenced now
    # "aa" (LRU) was evicted first and can never be falsely served; the
    # orphaned child "bb" chain entry is unreachable from the root walk
    assert al.probe(0, blk("aa")) is None
    al.decref(p3)
    al.decref(p4)
    al.decref(p5)
    assert c2 != c1


def test_pool_exhaustion_queues_instead_of_failing(setup):
    """A pool too small for every slot at once: requests WAIT for blocks
    (NoFreeBlocksError is internal backpressure, not a failure) and all
    complete exactly."""
    cfg, model, params = setup
    # 9 real pages of 8 tokens; each 24+16-token request reserves 5
    # blocks, so two can never run concurrently despite 2 free slots
    eng = InferenceEngine(params, cfg, num_slots=2, paged=True,
                          page_size=8, kv_pages=10)
    sched = Scheduler(eng, max_queue=8)
    handles, wants = [], []
    for i in range(4):
        prompt = _prompt(24, 300 + i)
        ref = generate_fast(params, cfg, prompt[None], 16,
                            temperature=0.9, top_k=7, seed=i)
        wants.append(ref[0, 24:].tolist())
        handles.append(sched.submit(prompt, SamplingParams(
            max_new_tokens=16, temperature=0.9, top_k=7, seed=i)))
    _drain(sched, handles)
    for h, want in zip(handles, wants):
        assert h.result(timeout=1) == want
    assert eng.stats.kv_blocks_in_use == 0
    assert eng.stats.active_slots == 0


def test_undersized_pool_rejected_at_construction(setup):
    """The constructor refuses a pool that couldn't serve even one full
    window (null + window + CoW headroom) — with that floor, EVERY
    request that passes the block_size validation also fits an idle
    pool, so a queued request can wait but never deadlock."""
    cfg, model, params = setup
    with pytest.raises(ValueError, match="kv_pages"):
        InferenceEngine(params, cfg, num_slots=1, paged=True,
                        page_size=8, kv_pages=9)      # needs >= 10
    eng = InferenceEngine(params, cfg, num_slots=4, paged=True,
                          page_size=8, kv_pages=10)   # minimum pool
    # worst-case full-window request still fits the minimal pool
    eng.validate(_prompt(32, 0), SamplingParams(max_new_tokens=32))


def test_paged_nan_quarantine_catches_slot_finishing_mid_chunk(setup):
    """Regression (review): the paged decode redirects a FINISHED row's
    block table to the null page, so the unpaged trick of reading the
    last scanned step's logits cannot witness a poison that struck
    mid-chunk — the programs must LATCH non-finite logits per iteration
    instead. Poison one slot's own pages, let it finish at iteration 2
    of a 4-step chunk: its tokens must come back poisoned (and the
    neighbor slot untouched)."""
    import jax.numpy as jnp

    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2, paged=True,
                          page_size=8, decode_chunk=4)
    slot, _ = eng.admit(_prompt(8, 1), SamplingParams(max_new_tokens=3))
    other, _ = eng.admit(_prompt(6, 2), SamplingParams(max_new_tokens=8))
    pg = int(eng._bt[slot, 0])
    eng._cache = jax.tree.map(lambda x: x.at[pg].set(jnp.nan), eng._cache)
    evs = eng.step()
    mine = [e for e in evs if e.slot == slot]
    assert mine and all(e.poisoned for e in mine)
    assert eng.stats.quarantined == 1
    assert all(not e.poisoned for e in evs if e.slot == other)
    assert eng.stats.kv_blocks_in_use > 0     # neighbor still holds pages


def test_failed_admission_releases_every_block(setup):
    """Regression (review): an exception inside the paged admission
    (here: an injected prefill fault) must unwind every pinned/allocated
    page — a failed request cannot permanently shrink the pool."""
    from gym_tpu.utils.resilience import faults

    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2, paged=True,
                          page_size=8)
    # seed the prefix cache so the failing admission also PINS hit pages
    _run_one(eng, _prompt(16, 60), SamplingParams(max_new_tokens=2))
    assert eng.stats.kv_blocks_in_use == 0
    cached_before = eng.stats.kv_blocks_cached
    faults.reset()
    faults.configure("serve.prefill:oserror")
    try:
        with pytest.raises(OSError):
            eng.admit(np.concatenate([_prompt(16, 60), _prompt(4, 61)]),
                      SamplingParams(max_new_tokens=4))
    finally:
        faults.reset()
    assert eng.stats.kv_blocks_in_use == 0
    assert eng.stats.kv_blocks_cached == cached_before
    # the pool still serves a full-window request afterwards
    got = _run_one(eng, _prompt(24, 62), SamplingParams(max_new_tokens=4,
                                                        top_k=3, seed=7))
    assert len(got) == 4


def test_starvation_guard_admits_blocked_head(setup):
    """Regression (review): a large-block-need head request must not be
    starved forever by a stream of small requests that keep the pool
    partially pinned — after `starvation_rounds` skipped rounds the
    scheduler holds admissions until the head fits."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2, paged=True,
                          page_size=8, kv_pages=10)    # 9 real pages
    sched = Scheduler(eng, max_queue=32, prefix_window=4,
                      starvation_rounds=2)
    # head needs the WHOLE pool (8 blocks); smalls need 2 each, with
    # staggered lengths so the two slots never drain simultaneously
    big = sched.submit(_prompt(40, 1), SamplingParams(max_new_tokens=24,
                                                      seed=1))
    smalls = [sched.submit(_prompt(8, 10 + i),
                           SamplingParams(max_new_tokens=6 + 2 * i,
                                          seed=i))
              for i in range(6)]
    for _ in range(3000):
        sched.step()
        if big.status is not RequestStatus.QUEUED:
            break
    assert big.status is not RequestStatus.QUEUED
    _drain(sched, [big] + smalls)
    assert len(big.result(timeout=1)) == 24
    for i, h in enumerate(smalls):
        assert len(h.result(timeout=1)) == 6 + 2 * i
    assert eng.stats.kv_blocks_in_use == 0


def test_starvation_guard_covers_prefix_priority(setup):
    """Regression (review): the guard must also bound being outscored —
    a cold-prefix head under a sustained hot-prefix stream would
    otherwise never win the window (it always HAS capacity, so the
    capacity-only guard never armed)."""
    cfg, model, params = setup
    shared = _prompt(16, 97)
    eng = InferenceEngine(params, cfg, num_slots=1, paged=True,
                          page_size=8)
    sched = Scheduler(eng, max_queue=64, prefix_window=4,
                      starvation_rounds=3)
    warm = sched.submit(np.concatenate([shared, _prompt(2, 98)]),
                        SamplingParams(max_new_tokens=2, seed=0))
    _drain(sched, [warm])
    cold = sched.submit(_prompt(18, 99), SamplingParams(
        max_new_tokens=2, seed=1))
    hot_seed = 200
    hots = []
    for _ in range(400):
        # keep the window saturated with hot-prefix competitors
        while sum(h.status is RequestStatus.QUEUED for h in hots) < 3:
            hots.append(sched.submit(
                np.concatenate([shared, _prompt(2, hot_seed)]),
                SamplingParams(max_new_tokens=2, seed=hot_seed)))
            hot_seed += 1
        sched.step()
        if cold.status is not RequestStatus.QUEUED:
            break
    assert cold.status is not RequestStatus.QUEUED
    _drain(sched, [cold] + hots)
    assert len(cold.result(timeout=1)) == 2


def test_scheduler_prefix_aware_admit_ordering(setup):
    """With one free slot and a cold-prefix request ahead of a
    hot-prefix request in the queue, the hot one is admitted first
    (within the lookahead window); on an unpaged engine the same queue
    stays strict FCFS."""
    cfg, model, params = setup
    shared = _prompt(16, 90)
    eng = InferenceEngine(params, cfg, num_slots=1, paged=True,
                          page_size=8)
    sched = Scheduler(eng, max_queue=8, prefix_window=4)
    # warm the prefix cache
    h0 = sched.submit(np.concatenate([shared, _prompt(2, 91)]),
                      SamplingParams(max_new_tokens=2, seed=0))
    _drain(sched, [h0])
    cold = sched.submit(_prompt(18, 92), SamplingParams(
        max_new_tokens=2, seed=1))
    hot = sched.submit(np.concatenate([shared, _prompt(2, 93)]),
                       SamplingParams(max_new_tokens=2, seed=2))
    sched.step()                       # admits ONE request into the slot
    assert hot.status in (RequestStatus.RUNNING, RequestStatus.DONE)
    assert cold.status is RequestStatus.QUEUED
    _drain(sched, [cold, hot])
    # unpaged: all scores 0 -> FCFS preserved
    engu = InferenceEngine(params, cfg, num_slots=1)
    schedu = Scheduler(engu, max_queue=8, prefix_window=4)
    first = schedu.submit(_prompt(6, 94), SamplingParams(
        max_new_tokens=2, seed=3))
    second = schedu.submit(_prompt(6, 95), SamplingParams(
        max_new_tokens=2, seed=4))
    schedu.step()
    assert first.status in (RequestStatus.RUNNING, RequestStatus.DONE)
    assert second.status is RequestStatus.QUEUED
    _drain(schedu, [first, second])


# -- observability ---------------------------------------------------------


def test_metrics_carry_paged_and_spec_observables(setup, tmp_path):
    """serve.csv engine rows + headline + read_headline all report
    kv_blocks_in_use / prefix_hit_blocks / spec_accept_rate."""
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2, paged=True,
                          page_size=8, spec_tokens=2)
    metrics = ServeMetrics(str(tmp_path), engine_log_every=1)
    sched = Scheduler(eng, max_queue=8, metrics=metrics)
    shared = _prompt(16, 40)
    hs = [sched.submit(np.concatenate([shared, _prompt(2, 41 + i)]),
                       SamplingParams(max_new_tokens=4, seed=i))
          for i in range(3)]
    while any(h.status in (RequestStatus.QUEUED, RequestStatus.RUNNING)
              for h in hs):
        sched.step()
        metrics.engine_tick(eng.stats, queue_depth=sched.queue_depth())
    metrics.sync()
    head = metrics.headline()
    assert head["requests_done"] == 3
    assert head["prefix_hit_blocks"] >= 2      # requests 2 and 3 hit
    assert head["spec_accept_rate"] is not None
    with open(os.path.join(str(tmp_path), "serve.csv")) as f:
        header = f.readline().strip().split(",")
    for col in ("kv_blocks_in_use", "prefix_hit_blocks",
                "spec_accept_rate"):
        assert col in header
    post = read_headline(os.path.join(str(tmp_path), "serve.csv"))
    assert post["prefix_hit_blocks"] == head["prefix_hit_blocks"]
    assert post["spec_accept_rate"] is not None
    metrics.close()


def test_unpaged_engine_reports_zero_paged_stats(setup):
    cfg, model, params = setup
    eng = InferenceEngine(params, cfg, num_slots=2)
    _run_one(eng, _prompt(6, 1), SamplingParams(max_new_tokens=3))
    assert eng.stats.kv_blocks_in_use == 0
    assert eng.stats.prefix_hit_blocks == 0
    assert eng.stats.spec_accept_rate() is None
    assert eng.stats.prefill_tokens == 8       # bucket(6) — comparable


# -- quantized serving (ISSUE 11) ------------------------------------------
#
# The oracle shape is unchanged: quantized streams are compared against
# the QUANTIZED unpaged reference (generate_fast under the same
# weights_dtype/kv_dtype config — both paths quantize identical K/V
# vectors to identical (int8, scale) pairs and attend over identical
# dequantized windows, so the streams match bitwise). f32-vs-int8
# divergence is a QUALITY observable, measured separately — never an
# exactness assert.

import dataclasses


def _quant(setup, weights_dtype="f32", kv_dtype="int8"):
    cfg, model, params = setup
    qcfg = dataclasses.replace(cfg, weights_dtype=weights_dtype,
                               kv_dtype=kv_dtype)
    from gym_tpu.serve.load import quantize_params
    return qcfg, quantize_params(params, qcfg)


@pytest.mark.parametrize("kv_dtype", ["f32", "int8"])
def test_prefix_sharing_exact_under_kv_dtype(setup, kv_dtype):
    """The kv_dtype param axis on the ISSUE 7 prefix-share oracle: the
    second admit reuses the resident (quantized) blocks — prefill
    shrinks to the suffix bucket — and both streams equal their solo
    quantized-unpaged generate_fast runs. Shared quantized pages are
    write-once (int8, scale) pairs, so sharing stays bit-stable."""
    qcfg, qparams = _quant(setup, kv_dtype=kv_dtype)
    shared = _prompt(24, 170)
    pa = np.concatenate([shared, _prompt(4, 171)])
    pb = np.concatenate([shared, _prompt(4, 172)])
    eng = InferenceEngine(qparams, qcfg, num_slots=2, paged=True,
                          page_size=8)
    ra = _run_one(eng, pa, SamplingParams(max_new_tokens=6,
                                          temperature=0.8, top_k=5,
                                          seed=1))
    tokens_first = eng.stats.prefill_tokens
    rb = _run_one(eng, pb, SamplingParams(max_new_tokens=6,
                                          temperature=0.8, top_k=5,
                                          seed=2))
    assert eng.stats.prefix_hit_blocks == 3
    assert eng.stats.prefill_tokens - tokens_first == 4
    assert ra == generate_fast(qparams, qcfg, pa[None], 6,
                               temperature=0.8, top_k=5,
                               seed=1)[0, 28:].tolist()
    assert rb == generate_fast(qparams, qcfg, pb[None], 6,
                               temperature=0.8, top_k=5,
                               seed=2)[0, 28:].tolist()


@pytest.mark.parametrize("kv_dtype", ["f32", "int8"])
def test_cow_triple_exact_under_kv_dtype(setup, kv_dtype):
    """CoW triple-exactness on the kv_dtype axis: a fully block-aligned
    re-admit copies the (int8, scale) page verbatim — the shared source
    page is not perturbed, so the third request is exact too."""
    qcfg, qparams = _quant(setup, kv_dtype=kv_dtype)
    p16 = _prompt(16, 180)
    eng = InferenceEngine(qparams, qcfg, num_slots=2, paged=True,
                          page_size=8)
    r1 = _run_one(eng, p16, SamplingParams(max_new_tokens=5, top_k=4,
                                           seed=3))
    before = eng.stats.prefill_tokens
    r2 = _run_one(eng, p16, SamplingParams(max_new_tokens=5, top_k=4,
                                           seed=4))
    assert eng.stats.prefill_tokens - before == 1
    r3 = _run_one(eng, p16, SamplingParams(max_new_tokens=5, top_k=4,
                                           seed=3))
    for r, seed in ((r1, 3), (r2, 4), (r3, 3)):
        assert r == generate_fast(qparams, qcfg, p16[None], 5, top_k=4,
                                  seed=seed)[0, 16:].tolist()


@pytest.mark.parametrize("kv_dtype", ["int8"])
def test_churn_isolated_under_int8_kv(setup, kv_dtype):
    """Churn isolation under int8 KV (weights int8 too — the full
    quantized hot path): mixed concurrent requests through one shared
    quantized pool all equal their solo quantized references, and the
    pool drains to zero."""
    qcfg, qparams = _quant(setup, weights_dtype="int8",
                           kv_dtype=kv_dtype)
    eng = InferenceEngine(qparams, qcfg, num_slots=2, decode_chunk=4,
                          paged=True, page_size=8)
    sched = Scheduler(eng, max_queue=8)
    handles, wants = [], []
    for i, (plen, mnew) in enumerate([(5, 7), (9, 12), (17, 9),
                                      (8, 15)]):
        prompt = _prompt(plen, 190 + i)
        ref = generate_fast(qparams, qcfg, prompt[None], mnew,
                            temperature=0.9, top_k=7, top_p=0.95, seed=i)
        wants.append(ref[0, plen:].tolist())
        handles.append(sched.submit(prompt, SamplingParams(
            max_new_tokens=mnew, temperature=0.9, top_k=7, top_p=0.95,
            seed=i)))
    _drain(sched, handles)
    for h, want in zip(handles, wants):
        assert h.result(timeout=1) == want
    assert eng.stats.kv_blocks_in_use == 0


def test_quantized_spec_decode_exact(setup):
    """Speculative decoding on the fully quantized path: draft/verify
    over int8 weights + int8 KV still emits the exact non-speculative
    quantized stream (rollback is a cursor rewind — quantized drafts sit
    past the cursor like f32 ones)."""
    qcfg, qparams = _quant(setup, weights_dtype="int8", kv_dtype="int8")
    prompt = _prompt(10, 121)
    ref = generate_fast(qparams, qcfg, prompt[None], 12, temperature=0.9,
                        top_k=7, seed=5)[0, 10:].tolist()
    spec = InferenceEngine(qparams, qcfg, num_slots=2, paged=True,
                           page_size=8, decode_chunk=3, spec_tokens=3)
    got = _run_one(spec, prompt, SamplingParams(max_new_tokens=12,
                                                temperature=0.9, top_k=7,
                                                seed=5))
    assert got == ref
    assert spec.stats.spec_drafted > 0


def test_quarantine_under_int8_kv(setup):
    """NaN quarantine still fails ONLY the poisoned slot under int8 KV:
    the f32 SCALE pool carries the poison (int8 payload cannot hold a
    NaN), dequant propagates it to that slot's logits, the latch
    catches it, and the neighbor stays clean."""
    import jax.numpy as jnp

    qcfg, qparams = _quant(setup, kv_dtype="int8")
    eng = InferenceEngine(qparams, qcfg, num_slots=2, paged=True,
                          page_size=8, decode_chunk=4)
    slot, _ = eng.admit(_prompt(8, 1), SamplingParams(max_new_tokens=3))
    other, _ = eng.admit(_prompt(6, 2), SamplingParams(max_new_tokens=8))
    pg = int(eng._bt[slot, 0])
    eng._cache = jax.tree.map(
        lambda x: x.at[pg].set(jnp.nan) if x.dtype == jnp.float32 else x,
        eng._cache)
    evs = eng.step()
    mine = [e for e in evs if e.slot == slot]
    assert mine and all(e.poisoned for e in mine)
    assert eng.stats.quarantined == 1
    assert all(not e.poisoned for e in evs if e.slot == other)


def test_int8_kv_capacity_4x_structural(setup):
    """The ISSUE 11 acceptance assert, structurally: at the SAME KV
    payload byte budget (4 int8 pages per f32 page) the int8 pool holds
    >= 4x the resident prefix blocks. Deterministic — sequential
    distinct one-block prompts, no timing anywhere."""
    cfg, model, params = setup
    qcfg, qparams = _quant(setup, kv_dtype="int8")

    def arm(c, p, kv_pages):
        eng = InferenceEngine(p, c, num_slots=2, paged=True, page_size=8,
                              kv_pages=kv_pages)
        for i in range(48):
            _run_one(eng, _prompt(8, 700 + i),
                     SamplingParams(max_new_tokens=2, seed=i))
        return eng

    f32_pages = 2 + cfg.block_size // 8        # minimum legal pool: 10
    int8_pages = 1 + (f32_pages - 1) * 4       # equal payload bytes: 37
    f32_eng = arm(cfg, params, f32_pages)
    int8_eng = arm(qcfg, qparams, int8_pages)
    f32_bytes = f32_eng.kv_pool_bytes()
    int8_bytes = int8_eng.kv_pool_bytes()
    assert int8_bytes["payload"] <= f32_bytes["payload"]
    assert f32_bytes["scales"] == 0 and int8_bytes["scales"] > 0
    assert (int8_eng.stats.kv_blocks_cached
            >= 4 * f32_eng.stats.kv_blocks_cached), (
        int8_eng.stats.kv_blocks_cached, f32_eng.stats.kv_blocks_cached)
    assert (int8_eng.kv_blocks_capacity_effective
            == 4 * (int8_pages - 1)
            > f32_eng.kv_blocks_capacity_effective)


def test_f32_vs_int8_divergence_measured_separately(setup):
    """The quality observable: f32 and int8 streams MAY diverge (that is
    the honest cost of the codec) — what is pinned is that each stream
    equals its OWN reference and the divergence is a measurement, not an
    exactness failure."""
    cfg, model, params = setup
    qcfg, qparams = _quant(setup, weights_dtype="int8", kv_dtype="int8")
    prompt = _prompt(12, 131)
    kw = dict(temperature=0.9, top_k=7, seed=9)
    ref_f32 = generate_fast(params, cfg, prompt[None], 16,
                            **kw)[0, 12:].tolist()
    ref_q = generate_fast(qparams, qcfg, prompt[None], 16,
                          **kw)[0, 12:].tolist()
    eng = InferenceEngine(qparams, qcfg, num_slots=2, paged=True,
                          page_size=8)
    got = _run_one(eng, prompt, SamplingParams(max_new_tokens=16, **kw))
    assert got == ref_q                       # exact vs OWN reference
    div = sum(a != b for a, b in zip(got, ref_f32)) / len(got)
    assert 0.0 <= div <= 1.0                  # measured, never asserted 0


def test_engine_rejects_bad_quant_dtypes(setup):
    cfg, model, params = setup
    with pytest.raises(ValueError, match="weights_dtype"):
        InferenceEngine(params, dataclasses.replace(
            cfg, weights_dtype="fp8"), num_slots=1)
    with pytest.raises(ValueError, match="kv_dtype"):
        InferenceEngine(params, dataclasses.replace(
            cfg, kv_dtype="int4"), num_slots=1)


def test_metrics_quant_columns_and_old_header_tolerance(setup, tmp_path):
    """serve.csv engine rows + headline + read_headline carry
    weights_dtype/kv_dtype; a pre-quantization CSV (old header) still
    aggregates — pinned like the paging and fleet schema bumps."""
    qcfg, qparams = _quant(setup, weights_dtype="int8", kv_dtype="int8")
    eng = InferenceEngine(qparams, qcfg, num_slots=2, paged=True,
                          page_size=8)
    metrics = ServeMetrics(str(tmp_path), engine_log_every=1)
    sched = Scheduler(eng, max_queue=8, metrics=metrics)
    h = sched.submit(_prompt(6, 41), SamplingParams(max_new_tokens=3))
    while h.status in (RequestStatus.QUEUED, RequestStatus.RUNNING):
        sched.step()
        metrics.engine_tick(eng.stats, queue_depth=sched.queue_depth())
    metrics.sync()
    head = metrics.headline()
    assert head["weights_dtype"] == "int8"
    assert head["kv_dtype"] == "int8"
    csv_path = os.path.join(str(tmp_path), "serve.csv")
    with open(csv_path) as f:
        header = f.readline().strip().split(",")
    assert "weights_dtype" in header and "kv_dtype" in header
    post = read_headline(csv_path)
    assert post["weights_dtype"] == "int8"
    assert post["kv_dtype"] == "int8"
    metrics.close()
    # old-header CSV (pre-quantization schema): aggregates fine, dtypes
    # simply absent
    old = tmp_path / "old.csv"
    old.write_text(
        "ts_s,kind,request_id,status,queue_depth,active_slots,"
        "prompt_tokens,new_tokens,ttft_s,avg_token_latency_s,"
        "cum_tokens,tokens_per_s\n"
        "0.5,request,r0,done,0,1,4,3,0.01,0.002,3,6.0\n")
    legacy = read_headline(str(old))
    assert legacy["requests_done"] == 1
    assert legacy["weights_dtype"] is None
    assert legacy["kv_dtype"] is None
