"""SDC defense (ISSUE 20): crc32c, checkpoint sidecars, wire crc,
corruption fault actions, quarantine collisions, watchdog attribution.

The layer-by-layer detection story: wrong bytes on disk are caught by
the checkpoint sidecar (``ChecksumMismatchError`` → quarantine), wrong
bytes on the wire by the per-frame crc (``FrameCorruptError`` →
failover), and wrong values in live device state by the training guard
(``test_guard_rollback.py``). Each detector is pinned here against its
matching injected fault."""

import json
import os
import shutil
import threading
import time

import numpy as np
import pytest

import jax

from gym_tpu.utils import integrity
from gym_tpu.utils.checkpoint import CheckpointManager, restore_params
from gym_tpu.utils.integrity import (ChecksumMismatchError, crc32c,
                                     tree_fingerprint,
                                     tree_fingerprint_host,
                                     verify_sidecar, write_sidecar)
from gym_tpu.utils.resilience import (FAULT_SITES, FaultRegistry,
                                      corrupt_point, dump_thread_stacks,
                                      faults)
from gym_tpu.serve import wire


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# -- crc32c ----------------------------------------------------------------


def test_crc32c_reference_vector():
    # the canonical Castagnoli check value (RFC 3720 B.4)
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
    # chaining == one-shot (streamed file hashing depends on it)
    data = bytes(range(256)) * 41  # deliberately not 8-aligned
    assert crc32c(data) == crc32c(data[100:], crc32c(data[:100]))


def test_crc32c_detects_single_bitflip():
    data = os.urandom(4096)
    ref = crc32c(data)
    flipped = bytearray(data)
    flipped[1234] ^= 0x10
    assert crc32c(bytes(flipped)) != ref


# -- checkpoint sidecars ---------------------------------------------------


def _make_step_dir(tmp_path, name="7"):
    d = tmp_path / name
    (d / "state").mkdir(parents=True)
    (d / "state" / "shard0").write_bytes(os.urandom(2048))
    (d / "meta.json").write_text('{"k": 1}')
    return str(d)


def test_sidecar_roundtrip_and_mismatch(tmp_path):
    d = _make_step_dir(tmp_path)
    write_sidecar(d, fingerprint={"sum": 1.5, "num_leaves": 3})
    assert verify_sidecar(d) is True
    rec = json.loads(open(os.path.join(d, "integrity.json")).read())
    assert rec["algo"] == "crc32c"
    assert "state/shard0" in rec["files"]
    assert rec["fingerprint"]["num_leaves"] == 3
    # the sidecar never hashes itself
    assert "integrity.json" not in rec["files"]
    # flip one byte in the shard → typed mismatch naming the file
    p = os.path.join(d, "state", "shard0")
    raw = bytearray(open(p, "rb").read())
    raw[100] ^= 0x1
    open(p, "wb").write(bytes(raw))
    with pytest.raises(ChecksumMismatchError, match="state/shard0"):
        verify_sidecar(d)


def test_sidecar_missing_file_and_old_format(tmp_path):
    d = _make_step_dir(tmp_path)
    # no sidecar at all = pre-integrity checkpoint: accepted, returns
    # False (soft-degrade — old checkpoints must keep restoring)
    assert verify_sidecar(d) is False
    write_sidecar(d)
    os.remove(os.path.join(d, "meta.json"))
    with pytest.raises(ChecksumMismatchError, match="file missing"):
        verify_sidecar(d)


def test_tree_fingerprint_host_and_device_agree():
    tree = {"a": np.arange(12, dtype=np.float32).reshape(3, 4),
            "b": {"c": np.ones(5, dtype=np.int32)},
            "skip": "not-an-array"}
    host = tree_fingerprint_host(tree)
    assert host["num_leaves"] == 2
    dev = float(np.asarray(jax.jit(tree_fingerprint)(
        {"a": tree["a"], "b": tree["b"]})))
    assert abs(dev - host["sum"]) < 1e-3


# -- corruption fault actions ----------------------------------------------


def test_spec_grammar_parses_bitflip_and_truncate():
    reg = FaultRegistry()
    reg.configure("checkpoint.bytes:bitflip=3@2,wire.frame:truncate@1-4,"
                  "dispatch.state:bitflip=1@5+")
    rules = reg._rules
    assert [(r.site, r.action, r.arg, r.first, r.last) for r in rules] == [
        ("checkpoint.bytes", "bitflip", 3.0, 2, 2),
        ("wire.frame", "truncate", 0.0, 1, 4),
        ("dispatch.state", "bitflip", 1.0, 5, None),
    ]
    with pytest.raises(ValueError, match="unknown fault action"):
        reg.install("wire.frame", "scramble")
    for site in ("checkpoint.bytes", "wire.frame", "dispatch.state"):
        assert site in FAULT_SITES


def test_corrupt_is_deterministic_and_windowed():
    reg = FaultRegistry()
    reg.configure("wire.frame:bitflip=2@2")
    data = bytes(range(200))
    assert reg.corrupt("wire.frame", data) == data        # hit 1: clean
    hit2 = reg.corrupt("wire.frame", data)                # hit 2: armed
    assert hit2 != data and len(hit2) == len(data)
    assert reg.corrupt("wire.frame", data) == data        # hit 3: clean
    # same (site, hit) → same wrong bytes: campaigns reproduce exactly
    reg2 = FaultRegistry()
    reg2.configure("wire.frame:bitflip=2@2")
    reg2.corrupt("wire.frame", data)
    assert reg2.corrupt("wire.frame", data) == hit2
    assert reg.hits("wire.frame") == 3


def test_truncate_action_and_corrupt_point_gating():
    reg = FaultRegistry()
    reg.configure("checkpoint.bytes:truncate=10")
    out = reg.corrupt("checkpoint.bytes", bytes(100))
    assert len(out) == 90
    reg.reset()
    reg.configure("checkpoint.bytes:truncate")  # default: half
    assert len(reg.corrupt("checkpoint.bytes", bytes(100))) == 50
    # module-level corrupt_point: inert (not even a hit) when unarmed
    data = b"payload"
    assert corrupt_point("wire.frame", data) is data
    assert faults.hits("wire.frame") == 0


def test_corruption_actions_inert_at_plain_fault_points():
    # a bitflip armed at a non-payload site must not crash fire()
    reg = FaultRegistry()
    reg.configure("dispatch.boundary:bitflip=1")
    reg.fire("dispatch.boundary")
    assert reg.hits("dispatch.boundary") == 1


# -- wire frame crc --------------------------------------------------------


def test_wire_frames_carry_and_strip_crc():
    frame = {"type": "chunk", "id": 11, "tokens": [5, 6, 7]}
    payload = wire.encode_frame(frame)[4:]
    raw = json.loads(payload)
    assert "crc" in raw and len(raw["crc"]) == 8
    # verified then STRIPPED: handlers never see the field
    assert wire.decode_payload(payload) == frame


def test_wire_crc_detects_content_corruption():
    frame = {"type": "chunk", "id": 11, "tokens": [5, 6, 7]}
    payload = bytearray(wire.encode_frame(frame)[4:])
    # corrupt a token digit so the JSON stays VALID — only the crc can
    # catch this one (the silent wrong-token case)
    idx = payload.index(b"5")
    payload[idx : idx + 1] = b"9"
    with pytest.raises(wire.FrameCorruptError, match="crc mismatch"):
        wire.decode_payload(bytes(payload))
    # FrameCorruptError IS a WireError: the router's mark-dead/failover
    # path handles it with zero special-casing
    assert issubclass(wire.FrameCorruptError, wire.WireError)


def test_wire_old_format_frames_accepted_unverified():
    frame = {"type": "done", "id": 3, "tokens_total": 9, "ttft_s": 0.1}
    old = json.dumps(frame, separators=(",", ":")).encode()
    assert wire.decode_payload(old) == frame


def test_wire_frame_fault_site_fires_in_encode():
    faults.install("wire.frame", "bitflip", arg=1, first=1, last=1)
    frame = {"type": "chunk", "id": 1, "tokens": [1, 2, 3]}
    corrupted = wire.encode_frame(frame)
    with pytest.raises(wire.WireError):
        wire.decode_payload(corrupted[4:])
    faults.reset()
    clean = wire.encode_frame(frame)
    assert wire.decode_payload(clean[4:]) == frame


def test_wire_truncate_fault_yields_typed_error():
    faults.install("wire.frame", "truncate", first=1, last=1)
    corrupted = wire.encode_frame({"type": "chunk", "id": 1,
                                   "tokens": [1, 2, 3]})
    # framing is intact (length prefix matches the truncated payload)
    # so the CONTENT layer must reject it
    (length,) = wire._LEN.unpack(corrupted[:4])
    assert length == len(corrupted) - 4
    with pytest.raises(wire.WireError):
        wire.decode_payload(corrupted[4:])


# -- quarantine suffix collisions ------------------------------------------


def test_double_quarantine_takes_next_suffix(tmp_path):
    mgr = CheckpointManager(str(tmp_path), "run", async_save=False)
    try:
        step = os.path.join(mgr.directory, "7")
        os.makedirs(os.path.join(step, "state"))
        # a PREVIOUS quarantine of the same step already holds -0
        os.makedirs(step + ".corrupt-0")
        mgr._quarantine_step(7)
        assert not os.path.exists(step)
        assert os.path.isdir(step + ".corrupt-1")
        assert os.path.isdir(step + ".corrupt-0")  # untouched
        # and a third round lands on -2
        os.makedirs(os.path.join(step, "state"))
        mgr._quarantine_step(7)
        assert os.path.isdir(step + ".corrupt-2")
    finally:
        mgr.close()


# -- end-to-end: corrupt checkpoint detected at restore --------------------


class _TinyLossModel:
    pass


def _fit_tiny(base, max_steps, resume="auto", **kw):
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from gym_tpu import Trainer
    from gym_tpu.data import ArrayDataset
    from gym_tpu.strategy import OptimSpec, SimpleReduceStrategy

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, batch, train=True):
            x, y = batch
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(16)(x))
            return optax.softmax_cross_entropy_with_integer_labels(
                nn.Dense(10)(x).astype(jnp.float32), y).mean()

    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, size=128).astype(np.int32)
    x = rng.normal(0, 0.3, size=(128, 8, 8)).astype(np.float32)
    for i, y in enumerate(labels):
        x[i, y % 8, :] += 1.5
    return Trainer(Tiny(), ArrayDataset(x, labels)).fit(
        strategy=SimpleReduceStrategy(OptimSpec("sgd", lr=0.05)),
        num_nodes=2, max_steps=max_steps, batch_size=16, minibatch_size=8,
        val_interval=0, show_progress=False, seed=3,
        checkpoint_interval=3, save_dir=os.path.join(base, "ckpt"),
        run_name="sdc", log_dir=os.path.join(base, "logs"),
        async_checkpoint=False, prefetch=False, resume=resume, **kw)


def test_corrupt_checkpoint_quarantined_at_restore(tmp_path):
    """The tentpole disk story end-to-end: every save writes a sidecar;
    an injected bitflip in the newest step is DETECTED at restore,
    quarantined through ``.corrupt-k``, and the run resumes from the
    older verified step — never restoring wrong bytes."""
    base = str(tmp_path)
    _fit_tiny(base, 6)
    run_dir = os.path.join(base, "ckpt", "sdc")
    assert os.path.exists(os.path.join(run_dir, "6", "integrity.json"))
    faults.install("checkpoint.bytes", "bitflip", arg=3)
    integrity.corrupt_checkpoint_files(os.path.join(run_dir, "6"))
    faults.reset()
    res = _fit_tiny(base, 9)
    assert res.steps == 9
    names = os.listdir(run_dir)
    assert any(n.startswith("6.corrupt-") for n in names), names
    # the corrupt step was re-saved cleanly on the way to 9
    assert verify_sidecar(os.path.join(run_dir, "9")) is True


def test_restore_params_skips_corrupt_newest(tmp_path):
    base = str(tmp_path)
    _fit_tiny(base, 6)
    run_dir = os.path.join(base, "ckpt", "sdc")
    faults.install("checkpoint.bytes", "bitflip", arg=2)
    integrity.corrupt_checkpoint_files(os.path.join(run_dir, "6"))
    faults.reset()
    step, params, _extra = restore_params(run_dir)
    assert step == 3  # fell back past the corrupt newest, READ-ONLY
    assert os.path.isdir(os.path.join(run_dir, "6"))  # not quarantined
    assert params


def test_checkpoint_bytes_fault_fires_during_save(tmp_path):
    """Arming checkpoint.bytes during the run corrupts the bytes AFTER
    the sidecar records the good ones — the write-path integration the
    chaos campaigns rely on."""
    base = str(tmp_path)
    faults.install("checkpoint.bytes", "bitflip", arg=2, first=2, last=2)
    try:
        _fit_tiny(base, 6)
    finally:
        faults.reset()
    run_dir = os.path.join(base, "ckpt", "sdc")
    assert verify_sidecar(os.path.join(run_dir, "3")) is True
    with pytest.raises(ChecksumMismatchError):
        verify_sidecar(os.path.join(run_dir, "6"))


# -- watchdog names the in-flight program ----------------------------------


def test_watchdog_dump_names_inflight_program():
    from gym_tpu.programs.registry import (ProgramRegistry,
                                           inflight_programs)

    reg = ProgramRegistry()
    release = threading.Event()
    entered = threading.Event()

    def slow_fn(x):
        entered.set()
        release.wait(10.0)
        return x

    wrapped = reg.track_jit("train_step[tiny]", {"lr": 0.1}, (), slow_fn)
    t = threading.Thread(target=wrapped, args=(np.zeros(3),), daemon=True)
    t.start()
    try:
        assert entered.wait(10.0)
        # the dump a hung run leaves behind attributes the wedged
        # dispatch to the registry key, not just "inside jax"
        dump = dump_thread_stacks("watchdog: test dump")
        assert "in-flight registry programs" in dump
        assert "train_step[tiny]" in dump
        assert t.ident in inflight_programs()
    finally:
        release.set()
        t.join(5.0)
    assert t.ident not in inflight_programs()  # cleared on exit


def test_dump_without_inflight_has_no_program_section():
    dump = dump_thread_stacks("hdr")
    assert "in-flight registry programs" not in dump
