"""Host-overlap pipeline (ISSUE 1): the background prefetcher must be an
EXECUTION detail — bit-identical training to the synchronous path — and the
async checkpoint writer must keep the dispatch loop moving while a save is
in flight.

Oracles:
- determinism: identical loss history for a fixed seed with prefetch on vs
  off, across steps_per_call shapes (incl. the remainder schedule);
- clean shutdown: a worker-side exception surfaces in the caller and no
  threads leak; a consumer-side exception mid-fit tears the worker down;
- checkpoint/resume mid-epoch with prefetch on: the saved iterator state
  is the consumed position, not the worker's read-ahead position;
- async save: ``save_async`` returns while the write is still in flight,
  and the written checkpoint equals the snapshot at enqueue time even
  though training (donation!) kept mutating the live state.
"""

import threading
import time

import jax
import numpy as np
import pytest

from gym_tpu import Trainer
from gym_tpu.data import ArrayDataset
from gym_tpu.data.prefetch import HostPrefetcher, dispatch_schedule
from gym_tpu.data.sampler import NodeBatchIterator, resolve_node_datasets
from gym_tpu.strategy import (DiLoCoStrategy, OptimSpec,
                              SimpleReduceStrategy)

from test_trainer_e2e import TinyLossModel, blobs


def _fit(ds, *, prefetch, spc=1, max_steps=7, seed=3, val=None, **kw):
    return Trainer(TinyLossModel(), ds, val).fit(
        strategy=SimpleReduceStrategy(OptimSpec("adamw", lr=1e-3)),
        num_nodes=8, max_steps=max_steps, batch_size=32, minibatch_size=16,
        steps_per_call=spc, val_size=16 if val is not None else 0,
        val_interval=3 if val is not None else 0, show_progress=False,
        seed=seed, prefetch=prefetch, log_dir="/tmp/gym_tpu_test_logs", **kw)


def _losses(res):
    return [l for _, l in res.history["train_loss"]]


def test_dispatch_schedule_mirrors_loop_quantization():
    # full calls on the multi-step program, remainder as single steps
    assert dispatch_schedule(0, 10, 4, True) == [4, 4, 1, 1]
    assert dispatch_schedule(2, 10, 4, True) == [4, 4]
    assert dispatch_schedule(0, 10, 4, False) == [1] * 10
    assert dispatch_schedule(0, 0, 4, True) == []
    assert sum(dispatch_schedule(3, 29, 5, True)) == 26


@pytest.mark.parametrize("spc,max_steps", [(1, 7), (4, 12), (4, 10)])
def test_prefetch_bit_identical_to_sync(spc, max_steps):
    """The determinism contract: same seed → bit-identical loss history
    with the prefetcher on or off ((4, 10) exercises the remainder
    schedule, where the tail runs on the single-step program)."""
    ds = blobs(512)
    off = _fit(ds, prefetch=False, spc=spc, max_steps=max_steps)
    on = _fit(ds, prefetch=True, spc=spc, max_steps=max_steps)
    assert _losses(off) == _losses(on)


def test_prefetch_stateful_dataset_stream_identical():
    """A dataset whose output depends on its take-call COUNTER (the
    augmentation-stream pattern, offline.CropAugmentedDataset): the
    prefetcher must issue the exact same call sequence as the sync path —
    no probe takes, no extra draws — or the streams diverge."""

    class CountingAugDataset:
        def __init__(self, n=256):
            self.inner = blobs(n)
            self.calls = 0

        def __len__(self):
            return len(self.inner)

        def take(self, idx):
            self.calls += 1
            x, y = self.inner.take(idx)
            # call-counter-dependent "augmentation"
            return x + 0.01 * self.calls, y

    off = _fit(CountingAugDataset(), prefetch=False, max_steps=6)
    on = _fit(CountingAugDataset(), prefetch=True, max_steps=6)
    assert _losses(off) == _losses(on)


def test_prefetch_epoch_boundary_determinism():
    """max_steps large enough that the iterator wraps epochs mid-run: the
    worker must reshuffle at the same draw positions the sync path does."""
    ds = blobs(128)  # 128 samples / (32 per step) = 4 steps per epoch
    off = _fit(ds, prefetch=False, max_steps=11)
    on = _fit(ds, prefetch=True, max_steps=11)
    assert _losses(off) == _losses(on)


def test_prefetch_worker_error_propagates_and_shuts_down():
    """A dataset that raises inside the WORKER thread: the exception must
    surface in the consumer's get(), and close() must leave no thread."""

    class PoisonDataset:
        def __init__(self, n=256):
            self.inner = blobs(n)
            self.calls = 0

        def __len__(self):
            return len(self.inner)

        def take(self, idx):
            self.calls += 1
            if self.calls > 3:
                raise RuntimeError("boom at draw 4")
            return self.inner.take(idx)

    dsets, sharded = resolve_node_datasets(PoisonDataset(), 2, is_val=False)
    it = NodeBatchIterator(dsets, 2, sharded=sharded, shuffle=True, seed=0)
    before = threading.active_count()
    pf = HostPrefetcher(it, lambda t: jax.device_put(t),
                        dispatch_schedule(0, 8, 1, False),
                        n_micro=1, micro_bs=4).start()
    with pytest.raises(RuntimeError, match="boom at draw 4"):
        for _ in range(8):
            pf.get()
    pf.close()
    pf.close()  # idempotent
    assert threading.active_count() == before


def test_fit_exception_cleans_up_threads(monkeypatch):
    """A consumer-side exception mid-fit (here: poisoned metric drain) must
    tear down the prefetch worker — no leaked threads, fit re-raises."""
    import gym_tpu.trainer as trainer_mod

    def poisoned(moments):
        raise RuntimeError("drain poisoned")

    monkeypatch.setattr(trainer_mod, "_replica_correlation", poisoned)
    before = threading.active_count()
    with pytest.raises(RuntimeError, match="drain poisoned"):
        Trainer(TinyLossModel(), blobs(256)).fit(
            strategy=SimpleReduceStrategy(OptimSpec("adamw", lr=1e-3)),
            num_nodes=8, max_steps=6, batch_size=32, minibatch_size=16,
            val_size=0, val_interval=0, correlation_interval=2,
            show_progress=False, prefetch=True,
            log_dir="/tmp/gym_tpu_test_logs")
    # worker threads are join()ed by the finally; allow a beat for the OS
    for _ in range(50):
        if threading.active_count() <= before:
            break
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_checkpoint_resume_mid_epoch_with_prefetch(tmp_path):
    """Resume mid-epoch with prefetch ON equals the straight run: the
    checkpoint must record the CONSUMED iterator position (the worker has
    already drawn ahead when the save fires)."""
    ds = blobs(256)  # epoch = 8 steps of 32; ckpt at 5 is mid-epoch

    def fit(max_steps, tmp):
        return _fit(ds, prefetch=True, max_steps=max_steps, seed=11,
                    checkpoint_interval=5, save_dir=tmp,
                    run_name="pf_resume")

    straight = _fit(ds, prefetch=True, max_steps=9, seed=11)
    fit(5, str(tmp_path))          # saves at step 5, mid-epoch
    resumed = fit(9, str(tmp_path))
    steps = [s for s, _ in resumed.history["train_loss"]]
    assert min(steps) == 5 and max(steps) == 8  # genuinely resumed
    for a, b in zip(jax.tree.leaves(straight.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)


def test_prefetch_with_eval_and_correlation_interleaved():
    """Interval firings (eval + correlation) with deferred host fetches:
    values and steps must match the synchronous run exactly."""
    ds = blobs(512)
    val = blobs(64, seed=1)

    def fit(prefetch):
        return Trainer(TinyLossModel(), ds, val).fit(
            strategy=DiLoCoStrategy(OptimSpec("adamw", lr=3e-2), H=5),
            num_nodes=4, max_steps=11, batch_size=32, minibatch_size=32,
            val_size=32, val_interval=4, correlation_interval=3,
            show_progress=False, seed=5, prefetch=prefetch,
            log_dir="/tmp/gym_tpu_test_logs")

    off, on = fit(False), fit(True)
    assert _losses(off) == _losses(on)
    assert off.history["local_loss"] == on.history["local_loss"]
    assert off.history["global_loss"] == on.history["global_loss"]
    assert (off.history["avg_model_correlation"]
            == on.history["avg_model_correlation"])


# -- async checkpointing ---------------------------------------------------


def test_save_async_does_not_block_caller(tmp_path):
    """Acceptance: an in-flight save must not stall the caller. The Orbax
    write is slowed to ~0.6 s; save_async must return in a fraction of
    that, and wait() must make the write durable."""
    from gym_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), "async_test", async_save=True)
    write_started = threading.Event()
    orig_write = mgr._write

    def slow_write(step, state, data_state, extra):
        write_started.set()
        time.sleep(0.6)
        orig_write(step, state, data_state, extra)

    mgr._write = slow_write
    state = {"w": jax.device_put(np.arange(1024.0, dtype=np.float32))}
    t0 = time.perf_counter()
    mgr.save_async(1, state, {"epoch": 0, "pos": [0]})
    enqueue_dt = time.perf_counter() - t0
    assert enqueue_dt < 0.3, f"save_async blocked for {enqueue_dt:.2f}s"
    assert write_started.wait(5.0)
    # the caller keeps working while the write is in flight
    assert mgr.latest_step() is None or mgr.latest_step() < 1
    mgr.wait()
    assert mgr.latest_step() == 1
    step, restored, data_state, _ = mgr.restore(state)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(1024.0, dtype=np.float32))
    assert data_state == {"epoch": 0, "pos": [0]}
    mgr.close()


def test_step_clock_advances_during_inflight_save(tmp_path, monkeypatch):
    """Acceptance bullet 4, end to end: while an Orbax write is in flight
    on the writer thread, the fit loop's step clock must KEEP ADVANCING.
    The write is held open until it directly observes further
    ``increment_step`` calls — an event, not a wall-clock race."""
    import gym_tpu.trainer as trainer_mod
    from gym_tpu.utils import checkpoint as ckpt_mod
    from gym_tpu.utils.logger import CSVLogger

    progress = {"steps": 0}

    class CountingLogger(CSVLogger):
        def increment_step(self):
            super().increment_step()
            progress["steps"] = self.step

    monkeypatch.setattr(trainer_mod, "CSVLogger", CountingLogger)

    advanced_during_save = threading.Event()
    orig_write = ckpt_mod.CheckpointManager._write

    def observing_write(self, step, state, data_state, extra):
        if not advanced_during_save.is_set():
            # hold the write open until the step clock moves (the final
            # at-max_steps save has nothing left to advance — the event
            # is already set by then)
            at_enqueue = progress["steps"]
            deadline = time.perf_counter() + 15.0
            while time.perf_counter() < deadline:
                if progress["steps"] > at_enqueue:
                    advanced_during_save.set()
                    break
                time.sleep(0.01)
        orig_write(self, step, state, data_state, extra)

    monkeypatch.setattr(ckpt_mod.CheckpointManager, "_write",
                        observing_write)
    res = _fit(blobs(512), prefetch=True, max_steps=12, seed=2,
               checkpoint_interval=3, save_dir=str(tmp_path),
               run_name="clock_test")
    assert res.steps == 12
    assert advanced_during_save.is_set(), \
        "dispatch loop stalled during the in-flight checkpoint write"
    # and the written checkpoint is usable
    from gym_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), "clock_test")
    assert mgr.latest_step() == 12
    mgr.close()


def test_writer_error_surfaces_on_wait(tmp_path):
    from gym_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), "err_test", async_save=True)

    def bad_write(step, state, data_state, extra):
        raise OSError("disk full")

    mgr._write = bad_write
    mgr.save_async(1, {"w": np.zeros(4, np.float32)}, {"pos": [0]})
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        mgr.wait()
    mgr.close()  # error already surfaced and cleared; shutdown is clean
