"""Host-resilience layer (ISSUE 2), in-process surface.

Covers the fault-injection registry's deterministic hit windows, the
retry policy, the watchdog, the hardened CheckpointManager (typed
errors, transient-failure retry in the async writer, newest-wins
coalescing under slow/failing in-flight writes, corrupt-newest restore
fallback, loud close), CSVLogger crash/resume semantics, and the
Trainer-level resume knob. The subprocess kill -9 / SIGTERM drills live
in ``tests/test_kill_harness.py``.
"""

import os
import shutil
import time

import jax
import numpy as np
import pytest

from gym_tpu import Trainer
from gym_tpu.strategy import OptimSpec, SimpleReduceStrategy
from gym_tpu.utils.checkpoint import (CheckpointManager,
                                      CheckpointNotFoundError)
from gym_tpu.utils.logger import CSVLogger
from gym_tpu.utils.resilience import (FaultRegistry, InjectedFault,
                                      RetryPolicy, Watchdog, fault_point,
                                      faults, with_retries)

from test_trainer_e2e import TinyLossModel, blobs

FAST_RETRY = RetryPolicy(attempts=4, base_delay=0.01, max_delay=0.02)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# -- fault registry -------------------------------------------------------


def test_fault_hit_windows():
    faults.install("checkpoint.write", "oserror", first=2, last=3)
    fault_point("checkpoint.write")  # hit 1: outside window
    with pytest.raises(InjectedFault):
        fault_point("checkpoint.write")  # hit 2
    with pytest.raises(InjectedFault):
        fault_point("checkpoint.write")  # hit 3
    fault_point("checkpoint.write")  # hit 4: past window
    assert faults.hits("checkpoint.write") == 4
    faults.reset()
    fault_point("checkpoint.write")  # no rules, no error
    assert faults.hits("checkpoint.write") == 0  # reset also clears counts


def test_fault_spec_parsing():
    r = FaultRegistry()
    r.configure("checkpoint.write:oserror@2, prefetch.fill:delay=0.5@3+ ,"
                "dispatch.boundary:kill@5-7")
    by_site = {rule.site: rule for rule in r._rules}
    assert by_site["checkpoint.write"].action == "oserror"
    assert (by_site["checkpoint.write"].first,
            by_site["checkpoint.write"].last) == (2, 2)
    assert by_site["prefetch.fill"].arg == 0.5
    assert (by_site["prefetch.fill"].first,
            by_site["prefetch.fill"].last) == (3, None)
    assert (by_site["dispatch.boundary"].first,
            by_site["dispatch.boundary"].last) == (5, 7)
    with pytest.raises(ValueError, match="unknown fault site"):
        r.configure("not.a.site:kill")
    with pytest.raises(ValueError, match="unknown fault action"):
        r.configure("checkpoint.write:explode")


def test_default_window_is_every_hit():
    faults.install("prefetch.fill", "oserror")
    for _ in range(3):
        with pytest.raises(InjectedFault):
            fault_point("prefetch.fill")


# -- retry policy ---------------------------------------------------------


def test_with_retries_recovers_from_transient():
    calls = {"n": 0}
    retries = []

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError(f"transient {calls['n']}")
        return "ok"

    out = with_retries(flaky, FAST_RETRY,
                       on_retry=lambda k, e, d: retries.append((k, d)))
    assert out == "ok" and calls["n"] == 3
    assert [k for k, _ in retries] == [1, 2]
    assert all(d >= 0 for _, d in retries)


def test_with_retries_exhaustion_raises_last():
    def always():
        raise OSError("persistent")

    with pytest.raises(OSError, match="persistent"):
        with_retries(always, RetryPolicy(attempts=2, base_delay=0.01),
                     on_retry=lambda *a: None)


def test_with_retries_zero_attempts_still_calls_once():
    # GYM_TPU_IO_RETRIES=0 must disable RETRYING, not skip the operation
    calls = {"n": 0}

    def op():
        calls["n"] += 1
        return "ran"

    assert with_retries(op, RetryPolicy(attempts=0)) == "ran"
    assert calls["n"] == 1


def test_with_retries_nonretryable_propagates_immediately():
    calls = {"n": 0}

    def typed():
        calls["n"] += 1
        raise ValueError("not IO")

    with pytest.raises(ValueError):
        with_retries(typed, FAST_RETRY)
    assert calls["n"] == 1


def test_retry_delay_backoff_and_bounds():
    p = RetryPolicy(attempts=8, base_delay=0.1, factor=2.0, max_delay=0.5,
                    jitter=0.25)
    for k in range(8):
        d = p.delay(k)
        assert 0.0 <= d <= 0.5 * 1.25
    # un-jittered growth is exponential then capped
    p0 = RetryPolicy(base_delay=0.1, factor=2.0, max_delay=0.5, jitter=0.0)
    assert [p0.delay(k) for k in range(4)] == [0.1, 0.2, 0.4, 0.5]


# -- watchdog -------------------------------------------------------------


def test_watchdog_fires_on_hung_region_with_stacks():
    fired = []
    wd = Watchdog(0.2, on_timeout=lambda label, msg: fired.append(
        (label, msg)), poll=0.05).start()
    try:
        with wd.watch("hung-dispatch"):
            time.sleep(0.7)
        assert fired, "watchdog did not fire"
        label, msg = fired[0]
        assert label == "hung-dispatch"
        assert "hung-dispatch" in msg and "MainThread" in msg
        assert wd.fired == "hung-dispatch"
    finally:
        wd.close()


def test_watchdog_quiet_on_fast_regions():
    fired = []
    wd = Watchdog(0.5, on_timeout=lambda *a: fired.append(a),
                  poll=0.05).start()
    try:
        for _ in range(5):
            with wd.watch("quick"):
                time.sleep(0.01)
        time.sleep(0.2)  # idle time does not count against any region
        assert not fired and wd.fired is None
    finally:
        wd.close()


# -- checkpoint manager ---------------------------------------------------


def _small_state():
    return {"w": jax.numpy.arange(8, dtype=jax.numpy.float32),
            "b": jax.numpy.ones((2, 3), dtype=jax.numpy.float32)}


def _mgr(tmp, **kw):
    kw.setdefault("retry_policy", FAST_RETRY)
    return CheckpointManager(str(tmp), "run", **kw)


def _corrupt_step(directory, step):
    """Zero-truncate every file in a committed step dir — a torn write
    that survived the atomic-rename protocol (e.g. zeroed-out blocks)."""
    root = os.path.join(directory, str(step))
    for dirpath, _, files in os.walk(root):
        for name in files:
            open(os.path.join(dirpath, name), "wb").close()


def test_restore_empty_raises_typed(tmp_path):
    mgr = _mgr(tmp_path)
    with pytest.raises(CheckpointNotFoundError, match="no checkpoint"):
        mgr.restore(_small_state())
    mgr.close()


def test_restore_explicit_missing_step_raises(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(2, _small_state(), {"epoch": 0})
    with pytest.raises(CheckpointNotFoundError, match="step 7"):
        mgr.restore(_small_state(), step=7)
    mgr.close()


def test_restore_skips_corrupt_newest_and_resaves(tmp_path, capfd):
    mgr = _mgr(tmp_path)
    s = _small_state()
    mgr.save(2, s, {"epoch": 0}, extra={"tag": 2})
    mgr.save(4, s, {"epoch": 1}, extra={"tag": 4})
    assert sorted(mgr.manager.all_steps()) == [2, 4]  # max_to_keep=2
    _corrupt_step(mgr.directory, 4)

    step, _, data_state, extra = mgr.restore(_small_state())
    assert step == 2 and data_state == {"epoch": 0} and extra["tag"] == 2
    assert "skipping unreadable checkpoint step 4" in capfd.readouterr().err
    # the corrupt dir is QUARANTINED (moved aside, not deleted) and the
    # step number is re-savable (Orbax's cached step list would
    # otherwise silently skip the save)
    assert mgr.manager.all_steps() == [2]
    assert os.path.isdir(os.path.join(mgr.directory, "4.corrupt-0"))
    mgr.save(4, s, {"epoch": 9}, extra={"tag": 44})
    step, _, data_state, extra = mgr.restore(_small_state())
    assert step == 4 and extra["tag"] == 44
    mgr.close()


def test_restore_all_corrupt_raises_typed_and_resaves(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(2, _small_state(), {"epoch": 0})
    _corrupt_step(mgr.directory, 2)
    with pytest.raises(CheckpointNotFoundError, match="no valid"):
        mgr.restore(_small_state())
    # the corrupt dirs were purged and the manager reloaded, so the
    # FRESH run that follows an all-corrupt fallthrough can re-save the
    # same step numbers (Orbax's cached step list would silently skip)
    mgr.save(2, _small_state(), {"epoch": 5})
    step, _, data_state, _ = mgr.restore(_small_state())
    assert step == 2 and data_state == {"epoch": 5}
    mgr.close()


def test_async_writer_retries_transient_oserror(tmp_path):
    faults.install("checkpoint.write", "oserror", first=1, last=2)
    mgr = _mgr(tmp_path)
    mgr.save_async(3, _small_state(), {"epoch": 0})
    mgr.wait()  # two injected failures were retried away
    assert faults.hits("checkpoint.write") == 3
    assert mgr.latest_step() == 3
    mgr.close()


def test_async_writer_device_get_retry(tmp_path):
    faults.install("checkpoint.device_get", "oserror", first=1, last=1)
    mgr = _mgr(tmp_path)
    mgr.save_async(3, _small_state(), {"epoch": 0})
    mgr.wait()
    assert faults.hits("checkpoint.device_get") == 2
    assert mgr.latest_step() == 3
    mgr.close()


def test_async_writer_exhausted_retries_surface_on_wait(tmp_path):
    faults.install("checkpoint.write", "oserror")  # every attempt fails
    mgr = _mgr(tmp_path)
    mgr.save_async(3, _small_state(), {"epoch": 0})
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        mgr.wait()
    mgr.close()


def test_coalescing_newest_wins_under_slow_inflight_write(tmp_path):
    faults.install("checkpoint.write", "delay", arg=0.4, first=1, last=1)
    mgr = _mgr(tmp_path)
    s = _small_state()
    mgr.save_async(1, s, {"epoch": 1})
    time.sleep(0.05)  # let the writer pick up step 1 (now slow in-flight)
    mgr.save_async(2, s, {"epoch": 2})  # PENDING...
    mgr.save_async(3, s, {"epoch": 3})  # ...replaced by newest
    mgr.wait()
    steps = sorted(mgr.manager.all_steps())
    assert 3 in steps and 2 not in steps  # step 2 coalesced away
    mgr.close()


def test_coalescing_newest_survives_failing_inflight_write(tmp_path):
    # the in-flight write fails terminally (each attempt slow AND
    # failing, so the newer save is enqueued while it is still dying);
    # the error is latched and surfaced, but the newest pending save
    # must still be written
    faults.install("checkpoint.write", "delay", arg=0.1, first=1,
                   last=FAST_RETRY.attempts)
    faults.install("checkpoint.write", "oserror", first=1,
                   last=FAST_RETRY.attempts)
    mgr = _mgr(tmp_path)
    s = _small_state()
    mgr.save_async(1, s, {"epoch": 1})
    time.sleep(0.05)
    mgr.save_async(3, s, {"epoch": 3})
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        mgr.wait()
    mgr.wait()  # error was consumed; the newest save is durable
    assert mgr.latest_step() == 3
    mgr.close()


def test_close_raises_on_hung_writer(tmp_path):
    faults.install("checkpoint.write", "delay", arg=1.5, first=1, last=1)
    mgr = _mgr(tmp_path, close_timeout=0.2)
    mgr.save_async(1, _small_state(), {"epoch": 0})
    time.sleep(0.05)
    with pytest.raises(RuntimeError, match="writer thread still alive"):
        mgr.close()
    for _ in range(200):  # let the delayed write finish, then close cleanly
        if not mgr._writer.is_alive():
            break
        time.sleep(0.1)
    mgr.close()


# -- CSVLogger resume -----------------------------------------------------


def _log_rows(run_dir, steps, resume_step=0, comm=64.0):
    lg = CSVLogger(max_steps=100, run_name="r", log_dir=str(run_dir),
                   show_progress=False, resume_step=resume_step)
    for s in steps:
        lg.log_train(1.0 + s, lr=0.1, comm_bytes=comm, step=s)
        lg.log_loss(2.0 + s, "local", step=s)
    lg.sync()
    lg.close()
    return os.path.join(str(run_dir), "r")


def _read(path):
    with open(path) as f:
        return f.read().splitlines()


def test_csv_resume_preserves_history_and_cum_comm(tmp_path):
    d = _log_rows(tmp_path, range(6))
    # resume from step 3: rows 0-2 survive, 3-5 dropped (they will be
    # re-logged by the resumed run), cum_comm continues from row 2
    _log_rows(tmp_path, range(3, 6), resume_step=3)
    rows = _read(os.path.join(d, "train.csv"))
    assert [r.split(",")[0] for r in rows[1:]] == ["0", "1", "2", "3", "4",
                                                  "5"]
    cums = [float(r.split(",")[4]) for r in rows[1:]]
    assert cums == [64.0 * (i + 1) for i in range(6)]  # continuous
    vrows = _read(os.path.join(d, "validation.csv"))
    assert [r.split(",")[0] for r in vrows[1:]] == ["0", "1", "2", "3", "4",
                                                    "5"]


def test_csv_resume_survives_sim_column_toggle(tmp_path):
    """A resumed fit that flips fit(network=...) changes the train.csv
    column count by one; the resume filter must keep the other format's
    pre-restore rows (padded/truncated to the new header), not silently
    discard the run's whole history."""
    # sim run (6 columns) resumed WITHOUT the sim column (5)
    lg = CSVLogger(max_steps=100, run_name="r", log_dir=str(tmp_path),
                   show_progress=False, sim=True)
    for s in range(4):
        lg.log_train(1.0 + s, lr=0.1, comm_bytes=64.0, step=s,
                     sim_step_s=0.5)
    lg.sync()
    lg.close()
    d = _log_rows(tmp_path, range(2, 4), resume_step=2)
    rows = _read(os.path.join(d, "train.csv"))
    assert [r.split(",")[0] for r in rows[1:]] == ["0", "1", "2", "3"]
    assert all(len(r.split(",")) == 5 for r in rows[1:])
    # and the reverse: plain rows kept when resuming WITH the sim column
    lg = CSVLogger(max_steps=100, run_name="r", log_dir=str(tmp_path),
                   show_progress=False, resume_step=3, sim=True)
    lg.log_train(4.0, lr=0.1, comm_bytes=64.0, step=3, sim_step_s=0.25)
    lg.sync()
    lg.close()
    rows = _read(os.path.join(d, "train.csv"))
    assert [r.split(",")[0] for r in rows[1:]] == ["0", "1", "2", "3"]
    assert rows[0].split(",")[-1] == "sim_step_s"
    assert rows[-1].split(",")[-1] == "0.250000"
    # old-format kept rows padded to the new width
    assert all(len(r.split(",")) == 6 for r in rows[1:])


def test_csv_resume_drops_torn_and_post_restore_rows(tmp_path):
    d = _log_rows(tmp_path, range(4))
    with open(os.path.join(d, "train.csv"), "a", newline="") as f:
        f.write("9,1.25,0.1,64,640\n")  # durable row past restore point
        f.write("1")  # torn final line: prefix of a row for step 10+
    _log_rows(tmp_path, range(2, 4), resume_step=2)
    rows = _read(os.path.join(d, "train.csv"))
    assert [r.split(",")[0] for r in rows[1:]] == ["0", "1", "2", "3"]


def test_csv_fresh_run_truncates(tmp_path):
    d = _log_rows(tmp_path, range(4))
    _log_rows(tmp_path, range(2), resume_step=0)
    rows = _read(os.path.join(d, "train.csv"))
    assert [r.split(",")[0] for r in rows[1:]] == ["0", "1"]


# -- Trainer-level resume -------------------------------------------------


def _fit(ds, max_steps, tmp, **kw):
    kw.setdefault("checkpoint_interval", 3)
    kw.setdefault("save_dir", tmp)
    kw.setdefault("run_name", "resil")
    return Trainer(TinyLossModel(), ds, None).fit(
        strategy=SimpleReduceStrategy(OptimSpec("sgd", lr=0.05)),
        num_nodes=2, max_steps=max_steps, batch_size=16, minibatch_size=8,
        val_interval=0, show_progress=False, seed=3,
        log_dir=os.path.join(tmp, "logs"),
        **kw,
    )


def _train_csv(tmp):
    with open(os.path.join(tmp, "logs", "resil", "train.csv")) as f:
        return f.read()


def test_fit_resumes_past_corrupt_newest_checkpoint(tmp_path):
    """Acceptance: restore demonstrably skips a deliberately corrupted
    newest checkpoint dir, resumes from the older one, and the stitched
    trajectory is bit-identical to an uninterrupted run."""
    ds = blobs(256, seed=5)
    straight, resume = str(tmp_path / "s"), str(tmp_path / "r")
    res_straight = _fit(ds, 10, straight)

    _fit(ds, 5, resume)  # checkpoints at steps 3 and 5 (max_to_keep=2)
    _corrupt_step(os.path.join(resume, "resil"), 5)
    res = _fit(ds, 10, resume)

    # genuinely fell back to step 3 (not 5): steps 3 and 4 were re-run
    steps = [s for s, _ in res.history["train_loss"]]
    assert min(steps) == 3 and max(steps) == 9
    assert _train_csv(resume) == _train_csv(straight)
    for a, b in zip(jax.tree.leaves(res_straight.params),
                    jax.tree.leaves(res.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    shutil.rmtree(str(tmp_path), ignore_errors=True)


def test_fit_default_run_name_resume_keeps_csv_history(tmp_path):
    # with run_name=None and checkpointing on, the checkpoint store AND
    # the CSV logger must agree on the pinned "default" run name — a
    # resume that restores the checkpoint but opens a fresh
    # run_<timestamp> log dir silently orphans the pre-crash history
    ds = blobs(256, seed=5)
    d = str(tmp_path / "noname")
    _fit(ds, 5, d, run_name=None)
    _fit(ds, 10, d, run_name=None)
    path = os.path.join(d, "logs", "default", "train.csv")
    with open(path) as f:
        steps = [r.split(",")[0] for r in f.read().splitlines()[1:]]
    assert steps == [str(i) for i in range(10)]
    shutil.rmtree(str(tmp_path), ignore_errors=True)


def test_fit_resume_never_starts_over(tmp_path):
    ds = blobs(128, seed=6)
    d = str(tmp_path / "fresh")
    _fit(ds, 4, d)
    res = _fit(ds, 4, d, resume="never")
    steps = [s for s, _ in res.history["train_loss"]]
    assert min(steps) == 0 and max(steps) == 3  # did not resume
    # and the purged dir was re-populated by the fresh run's checkpoints
    mgr = CheckpointManager(d, "resil")
    assert mgr.latest_step() == 4
    mgr.close()
    shutil.rmtree(str(tmp_path), ignore_errors=True)


def test_fit_resume_explicit_step_missing_raises(tmp_path):
    ds = blobs(128, seed=6)
    with pytest.raises(CheckpointNotFoundError):
        _fit(ds, 4, str(tmp_path / "x"), resume=7)
    shutil.rmtree(str(tmp_path), ignore_errors=True)


def test_fit_resume_zero_is_a_step_pin_not_never(tmp_path):
    # resume=0 must mean "checkpoint step 0" (missing → typed error),
    # NOT fall into the `0 == False` purge-and-start-over path
    ds = blobs(128, seed=6)
    d = str(tmp_path / "zero")
    _fit(ds, 4, d)
    with pytest.raises(CheckpointNotFoundError):
        _fit(ds, 4, d, resume=0)
    # and the existing checkpoints were NOT purged by the attempt
    mgr = CheckpointManager(d, "resil")
    assert mgr.latest_step() == 4
    mgr.close()
    shutil.rmtree(str(tmp_path), ignore_errors=True)


def test_fit_resume_step_without_checkpointing_raises(tmp_path):
    ds = blobs(128, seed=6)
    with pytest.raises(ValueError, match="requires save_dir"):
        _fit(ds, 4, str(tmp_path / "x"), resume=7, checkpoint_interval=None,
             save_dir=None)
    with pytest.raises(ValueError, match="resume must be"):
        _fit(ds, 4, str(tmp_path / "x"), resume="latest")
    shutil.rmtree(str(tmp_path), ignore_errors=True)


def test_fit_preempted_by_sigterm_emergency_checkpoint(tmp_path):
    """In-process preemption drill: a SIGTERM delivered at a dispatch
    boundary (via fault injection, so the timing is deterministic) makes
    fit take one synchronous emergency checkpoint and return cleanly
    with preempted=True; a later fit(resume='auto') continues to a
    trajectory bit-identical to an uninterrupted run."""
    ds = blobs(256, seed=5)
    straight, pre = str(tmp_path / "s"), str(tmp_path / "p")
    _fit(ds, 10, straight)

    faults.install("dispatch.boundary", "sigterm", first=5, last=5)
    res = _fit(ds, 10, pre)
    faults.reset()
    assert res.preempted and 0 < res.steps < 10
    # the emergency checkpoint is the newest step and matches res.steps
    mgr = CheckpointManager(pre, "resil")
    assert mgr.latest_step() == res.steps
    mgr.close()

    res2 = _fit(ds, 10, pre)
    assert not res2.preempted and res2.steps == 10
    assert [s for s, _ in res2.history["train_loss"]][0] == res.steps
    assert _train_csv(pre) == _train_csv(straight)
    shutil.rmtree(str(tmp_path), ignore_errors=True)
