"""Ring attention / context parallelism (SURVEY §5.7 — capability the
reference lacks; first-class here).

Oracles: (1) the ring op is numerically identical to dense causal attention
on the full sequence; (2) a context-parallel GPT training run produces the
same losses and parameters as the same-seed dense run — sequence sharding is
an execution detail, not a semantics change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.6 promoted shard_map out of experimental
    shard_map = jax.shard_map
except AttributeError:  # jax 0.4.x (whose check_rep chokes on scan carries)
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, **kw):
        kw.pop("check_vma", None)  # the new-API spelling of check_rep
        return _shard_map_legacy(f, check_rep=False, **kw)

from gym_tpu import Trainer
from gym_tpu.data import ArrayDataset
from gym_tpu.models.nanogpt import GPT, GPTConfig
from gym_tpu.ops.attention import dense_causal_attention
from gym_tpu.ops.flash_attention import flash_causal_attention
from gym_tpu.parallel.ring_attention import ring_causal_attention
from gym_tpu.strategy import DiLoCoStrategy, OptimSpec, SimpleReduceStrategy


def _shard_ring(q, k, v, n, devices):
    mesh = Mesh(np.array(devices[:n]), ("seq",))
    spec = P(None, None, "seq", None)

    def f(q, k, v):
        return ring_causal_attention(q, k, v, axis_name="seq")

    return jax.jit(
        shard_map(f, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
    )(q, k, v)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_ring_matches_dense(devices8, n):
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 3, 64, 8)), jnp.float32)
        for _ in range(3)
    )
    with jax.default_matmul_precision("highest"):
        out = _shard_ring(q, k, v, n, devices8)
        ref = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=1e-5)


def test_ring_bf16(devices8):
    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 32, 8)), jnp.bfloat16)
        for _ in range(3)
    )
    out = _shard_ring(q, k, v, 4, devices8)
    ref = dense_causal_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=0.05
    )


def test_ring_dropout_semantics(devices8):
    """Dropout drops attention *probabilities* (dense semantics): with
    rate→0⁺ behavior intact, outputs stay finite, differ from the
    deterministic pass, and keep the softmax-denominator normalization
    (row means bounded by value range)."""
    rng = np.random.default_rng(3)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 32, 8)), jnp.float32)
        for _ in range(3)
    )
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    spec = P(None, None, "seq", None)

    def f(q, k, v):
        return ring_causal_attention(
            q, k, v, axis_name="seq", dropout_rate=0.5,
            dropout_rng=jax.random.PRNGKey(0), deterministic=False,
        )

    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)
    )(q, k, v)
    ref = _shard_ring(q, k, v, 4, jax.devices())
    assert np.all(np.isfinite(np.asarray(out)))
    assert not np.allclose(np.asarray(out), np.asarray(ref))
    # denominator undropped → magnitudes stay in the value range ballpark
    assert np.abs(np.asarray(out)).max() < np.abs(np.asarray(v)).max() * 4


def test_flash_fallback_matches_dense():
    """Off-TPU the flash path must fall back to dense exactly."""
    rng = np.random.default_rng(2)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 16, 8)), jnp.float32)
        for _ in range(3)
    )
    np.testing.assert_array_equal(
        np.asarray(flash_causal_attention(q, k, v)),
        np.asarray(dense_causal_attention(q, k, v)),
    )


def _char_stream_ds(n=512, t=32, vocab=17, seed=0):
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, vocab, size=(n, t), dtype=np.int64)
    tgt = np.roll(idx, -1, axis=1)
    return ArrayDataset(idx, tgt)


def _fit_gpt(cfg, cp, num_nodes=2, steps=6, seed=3):
    ds = _char_stream_ds(seed=seed)
    res = Trainer(GPT(cfg), ds, None).fit(
        strategy=SimpleReduceStrategy(OptimSpec("adamw", lr=1e-3)),
        num_nodes=num_nodes, max_steps=steps, batch_size=8,
        minibatch_size=8, cp=cp, val_interval=0, show_progress=False,
        seed=7, log_dir="/tmp/gym_tpu_test_logs",
    )
    return res


@pytest.mark.slow
def test_context_parallel_gpt_matches_dense(devices8):
    """Same seed, same data: cp=2 ring GPT ≡ cp=1 dense GPT."""
    base = dict(block_size=32, vocab_size=17, n_layer=2, n_head=2,
                n_embd=32, dropout=0.0, bias=True)
    with jax.default_matmul_precision("highest"):
        res_dense = _fit_gpt(GPTConfig(**base), cp=1)
        res_ring = _fit_gpt(
            GPTConfig(**base, attn_impl="ring", seq_axis="seq"), cp=2
        )
    l_dense = [l for _, l in res_dense.history["train_loss"]]
    l_ring = [l for _, l in res_ring.history["train_loss"]]
    np.testing.assert_allclose(l_ring, l_dense, rtol=2e-4, atol=2e-4)
    for a, b in zip(jax.tree.leaves(res_dense.params),
                    jax.tree.leaves(res_ring.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-3)


@pytest.mark.slow
def test_context_parallel_with_diloco(devices8):
    """CP composes with a communication strategy (seq axis orthogonal to the
    node axes): 4 nodes × cp=2 on 8 devices, DiLoCo outer loop fires."""
    cfg = GPTConfig(block_size=32, vocab_size=17, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True,
                    attn_impl="ring", seq_axis="seq")
    ds = _char_stream_ds()
    res = Trainer(GPT(cfg), ds, _char_stream_ds(seed=9)).fit(
        strategy=DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=1e-3), H=2),
        num_nodes=4, max_steps=5, batch_size=8, minibatch_size=8, cp=2,
        val_size=8, val_interval=2, show_progress=False,
        log_dir="/tmp/gym_tpu_test_logs",
    )
    losses = [l for _, l in res.history["train_loss"]]
    assert np.all(np.isfinite(losses))
    comm = [c for _, c in res.history["comm_bytes"]]
    assert any(c > 0 for c in comm)  # outer round communicated
    for leaf in jax.tree.leaves(res.params):
        assert np.all(np.isfinite(leaf))


@pytest.mark.slow
@pytest.mark.parametrize("n", [2, 4])
def test_ring_kernel_blocks_match_dense(devices8, n):
    """The Pallas-fused block path (diag causal kernel + gated full-block
    kernels merged in lse space) is the same math as dense causal
    attention — values AND gradients (the lse cotangent must flow through
    the merge into ds). Runs the TPU kernels in the Pallas interpreter;
    Tl = 512/256 ≥ 128 makes the kernel path eligible."""
    from gym_tpu.ops import fused_attention
    from gym_tpu.parallel.ring_attention import _kernel_blocks_ok

    rng = np.random.default_rng(3)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 1024, 16)), jnp.float32)
        for _ in range(3)
    )
    fused_attention.INTERPRET = True
    try:
        assert _kernel_blocks_ok(q[:, :, : 1024 // n])
        mesh = Mesh(np.array(devices8[:n]), ("seq",))
        spec = P(None, None, "seq", None)

        def loss_ring(q, k, v):
            def f(q, k, v):
                return ring_causal_attention(q, k, v, axis_name="seq")
            # check_vma=False: pallas_call out_shapes carry no vma info
            # (the NodeRuntime programs run with the same setting)
            out = shard_map(f, mesh=mesh, in_specs=(spec,) * 3,
                                out_specs=spec, check_vma=False)(q, k, v)
            return (out.astype(jnp.float32) ** 2).mean(), out

        def loss_dense(q, k, v):
            out = dense_causal_attention(q, k, v)
            return (out.astype(jnp.float32) ** 2).mean(), out

        with jax.default_matmul_precision("highest"):
            (_, out), g_ring = jax.value_and_grad(
                loss_ring, argnums=(0, 1, 2), has_aux=True)(q, k, v)
            (_, ref), g_dense = jax.value_and_grad(
                loss_dense, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    finally:
        fused_attention.INTERPRET = False
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=1e-4)


def _zigzag_perm(t, n):
    """Global row order that makes contiguous per-device shards hold the
    zig-zag layout: device i gets half-chunks i and 2n-1-i."""
    h = t // (2 * n)
    idx = []
    for i in range(n):
        idx.extend(range(i * h, (i + 1) * h))
        idx.extend(range((2 * n - 1 - i) * h, (2 * n - i) * h))
    return np.array(idx)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_ring_zigzag_matches_dense(devices8, n):
    """Zig-zag schedule ≡ dense causal attention (rows permuted into the
    zig-zag device layout and back)."""
    rng = np.random.default_rng(5)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 3, 64, 8)), jnp.float32)
        for _ in range(3)
    )
    perm = _zigzag_perm(64, n)
    mesh = Mesh(np.array(devices8[:n]), ("seq",))
    spec = P(None, None, "seq", None)

    def f(q, k, v):
        return ring_causal_attention(q, k, v, axis_name="seq",
                                     layout="zigzag")

    with jax.default_matmul_precision("highest"):
        out = jax.jit(shard_map(
            f, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec
        ))(q[..., perm, :], k[..., perm, :], v[..., perm, :])
        ref = dense_causal_attention(q, k, v)
    inv = np.argsort(perm)
    np.testing.assert_allclose(np.asarray(out)[..., inv, :],
                               np.asarray(ref), atol=2e-6, rtol=1e-5)


def test_ring_zigzag_dropout_finite(devices8):
    """The dense-zigzag dropout path: finite, differs from deterministic,
    keeps denominator normalization."""
    rng = np.random.default_rng(6)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 32, 8)), jnp.float32)
        for _ in range(3)
    )
    perm = _zigzag_perm(32, 4)
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    spec = P(None, None, "seq", None)

    def f(det):
        def g(q, k, v):
            return ring_causal_attention(
                q, k, v, axis_name="seq", layout="zigzag",
                dropout_rate=0.5, dropout_rng=jax.random.PRNGKey(0),
                deterministic=det)
        return jax.jit(shard_map(
            g, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec
        ))(q[..., perm, :], k[..., perm, :], v[..., perm, :])

    out, det = f(False), f(True)
    assert np.all(np.isfinite(np.asarray(out)))
    assert not np.allclose(np.asarray(out), np.asarray(det))
    assert np.abs(np.asarray(out)).max() < np.abs(np.asarray(v)).max() * 4


@pytest.mark.slow
@pytest.mark.parametrize("n", [2, 4])
def test_ring_zigzag_kernel_blocks_match_dense(devices8, n):
    """Pallas-fused zig-zag blocks: same values AND gradients as dense
    causal attention (lse cotangents flow through the gated merges)."""
    from gym_tpu.ops import fused_attention

    rng = np.random.default_rng(7)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 1024, 16)), jnp.float32)
        for _ in range(3)
    )
    perm = _zigzag_perm(1024, n)
    inv = np.argsort(perm)
    fused_attention.INTERPRET = True
    try:
        mesh = Mesh(np.array(devices8[:n]), ("seq",))
        spec = P(None, None, "seq", None)

        def loss_ring(q, k, v):
            def f(q, k, v):
                return ring_causal_attention(q, k, v, axis_name="seq",
                                             layout="zigzag")
            out = shard_map(f, mesh=mesh, in_specs=(spec,) * 3,
                                out_specs=spec, check_vma=False)(
                q[..., perm, :], k[..., perm, :], v[..., perm, :])
            out = out[..., inv, :]
            return (out.astype(jnp.float32) ** 2).mean(), out

        def loss_dense(q, k, v):
            out = dense_causal_attention(q, k, v)
            return (out.astype(jnp.float32) ** 2).mean(), out

        with jax.default_matmul_precision("highest"):
            (_, out), g_ring = jax.value_and_grad(
                loss_ring, argnums=(0, 1, 2), has_aux=True)(q, k, v)
            (_, ref), g_dense = jax.value_and_grad(
                loss_dense, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    finally:
        fused_attention.INTERPRET = False
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=1e-4)
