"""Strategy-semantics tests against the reference algorithms' math
(citations in each strategy module). These run the pure (init, step) API
directly on tiny pytrees over the CPU node mesh — the unit-test layer the
reference never had (SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gym_tpu.parallel import NodeRuntime
from gym_tpu.strategy import (DiLoCoStrategy, FedAvgStrategy, OptimSpec,
                              PartitionedIndexSelector, RandomIndexSelector,
                              ShuffledSequentialIndexSelector,
                              SimpleReduceStrategy, SPARTADiLoCoStrategy,
                              SPARTAStrategy, ZeroReduceStrategy)


def _noloco_int8(**kw):
    from gym_tpu.strategy import NoLoCoStrategy
    return NoLoCoStrategy(optim_spec=OptimSpec("sgd", lr=0.1),
                          codec="int8", **kw)


def _demo_outer(**kw):
    from gym_tpu.strategy import DecoupledMomentumStrategy
    return DecoupledMomentumStrategy(optim_spec=OptimSpec("sgd", lr=0.1),
                                     frac=0.2, **kw)


def make_harness(strategy, num_nodes, params_np, max_steps=100,
                 devices=None):
    """Compile per-step strategy application over the node mesh.

    params_np: dict of [K, ...] arrays (per-node initial params).
    Returns (step_fn, params, state) with host-side step loop.
    """
    rt = NodeRuntime.create(num_nodes, devices)
    strategy.finalize(max_steps)
    strategy.bind_ctx(rt.ctx)

    init = rt.compile(lambda p: strategy.init(p), donate_state=False)
    params = rt.shard_batch(params_np)
    state = init(params)

    raw = rt.compile(
        lambda p, s, g, t: strategy.step(g, p, s, t, rt.ctx),
        donate_state=False,
    )

    def step_fn(params, state, grads_np, t):
        grads = rt.shard_batch(grads_np)
        tvec = rt.shard_batch(np.full(num_nodes, t, np.int32))
        p, s, m = raw(params, state, grads, tvec)
        return p, s, jax.device_get(m)

    return rt, step_fn, params, state


@pytest.mark.parametrize("strategy_fn", [
    lambda: SimpleReduceStrategy(OptimSpec("sgd", lr=0.1)),
    lambda: ZeroReduceStrategy(OptimSpec("sgd", lr=0.1)),
    lambda: DiLoCoStrategy(optim_spec=OptimSpec("sgd", lr=0.1), H=2),
    lambda: FedAvgStrategy(inner_optim=OptimSpec("sgd", lr=0.1), H=2),
    lambda: SPARTAStrategy(inner_optim=OptimSpec("sgd", lr=0.1),
                           p_sparta=0.5),
    lambda: SPARTADiLoCoStrategy(optim_spec=OptimSpec("sgd", lr=0.1),
                                 p_sparta=0.5, H=2),
    lambda: DiLoCoStrategy(optim_spec=OptimSpec("sgd", lr=0.1), H=2,
                           codec="int4"),
    lambda: _noloco_int8(H=2),
    lambda: _demo_outer(H=2),
], ids=["simple_reduce", "zero_reduce", "diloco", "fedavg", "sparta",
        "sparta_diloco", "diloco_int4", "noloco_int8", "demo_outer"])
def test_comm_bytes_metric_normalized(strategy_fn):
    """Every strategy's comm_bytes metric flows through one helper
    (strategy.base.comm_metric): float32, scalar per node — the
    strategies used to return a mix of Python floats and jnp arrays,
    which the logging/trace layers then had to special-case (ISSUE 3
    satellite). DeMo is covered separately in test_demo.py (its step
    needs the DCT harness)."""
    K = 4
    params0 = {"w": np.ones((K, 6), np.float32),
               "b": np.ones((K, 3), np.float32)}
    grads = {"w": np.ones((K, 6), np.float32),
             "b": np.ones((K, 3), np.float32)}
    strat = strategy_fn()
    rt, step_fn, params, state = make_harness(strat, K, params0)
    for t in (0, 2):
        params, state, m = step_fn(params, state, grads, t)
        comm = m["comm_bytes"]
        # [K] after the harness gathers the per-node scalar metric
        assert comm.shape == (K,), comm.shape
        assert comm.dtype == np.float32, comm.dtype
        assert np.all(np.isfinite(comm))


def test_simple_reduce_is_grad_average():
    """K-node SimpleReduce with per-node grads g_k must equal a single
    SGD step on mean(g_k) — DDP correctness (reference strategy.py:128-142)."""
    K = 4
    params0 = {"w": np.tile(np.ones((1, 3), np.float32), (K, 1))}
    grads = {"w": np.arange(K * 3, dtype=np.float32).reshape(K, 3)}
    strat = SimpleReduceStrategy(OptimSpec("sgd", lr=0.1))
    rt, step_fn, params, state = make_harness(strat, K, params0)
    params, state, m = step_fn(params, state, grads, 0)
    out = jax.device_get(params)["w"]
    expect = 1.0 - 0.1 * grads["w"].mean(axis=0)
    for k in range(K):
        np.testing.assert_allclose(out[k], expect, rtol=1e-6)
    assert np.all(m["comm_bytes"] > 0)


def test_fedavg_h_gating_and_sync():
    """Nodes drift for H−1 steps then snap to the average
    (reference federated_averaging.py:108-111 gate semantics)."""
    K, H = 4, 3
    params0 = {"w": np.zeros((K, 2), np.float32)}
    strat = FedAvgStrategy(inner_optim=OptimSpec("sgd", lr=1.0), H=H)
    rt, step_fn, params, state = make_harness(strat, K, params0)
    # node k's constant grad is -k, so under lr=1 SGD node k drifts by +k
    # per step until a sync snaps everyone to the average
    grads = {"w": np.repeat(-np.arange(K, dtype=np.float32)[:, None], 2, axis=1)}
    comm_log = []
    for t in range(2 * H + 1):
        params, state, m = step_fn(params, state, grads, t)
        comm_log.append(float(m["comm_bytes"][0]))
    out = jax.device_get(params)["w"]
    # comm only on steps where t % H == 0 and t > 0  (t = pre-increment step)
    for t, c in enumerate(comm_log):
        if t % H == 0 and t > 0:
            assert c > 0, (t, comm_log)
        else:
            assert c == 0, (t, comm_log)
    # the last executed step (t=2H) fired a sync: all nodes identical
    for k in range(1, K):
        np.testing.assert_allclose(out[k], out[0], rtol=1e-5)


def test_fedavg_islands_partial_averaging():
    """island_size=2 over 4 nodes: each island averages internally; the two
    islands generally differ (reference federated_averaging.py:26-69)."""
    K = 4
    params0 = {"w": np.repeat(np.arange(K, dtype=np.float32)[:, None], 4, 1)}
    strat = FedAvgStrategy(inner_optim=OptimSpec("sgd", lr=0.0), H=1,
                           island_size=2)
    rt, step_fn, params, state = make_harness(strat, K, params0)
    zero_g = {"w": np.zeros((K, 4), np.float32)}
    params, state, m = step_fn(params, state, zero_g, 1)  # t=1 → comm fires
    out = jax.device_get(params)["w"][:, 0]  # per-node scalar value
    # Each node's value must be the mean of exactly 2 of {0,1,2,3}, the
    # global mean of values must be preserved, and each value appears twice.
    np.testing.assert_allclose(np.sort(out)[::2], np.sort(out)[1::2])
    np.testing.assert_allclose(out.sum(), np.arange(K).sum(), rtol=1e-6)
    # islands have size 2, so nodes sharing a value come in groups of 2
    # (or 4 if the two random islands happen to share the same mean)
    groups = {tuple(np.argwhere(np.isclose(out, v)).ravel()) for v in out}
    assert all(len(g) % 2 == 0 for g in groups)
    # each value is the mean of two distinct originals → 2*v is an integer
    np.testing.assert_allclose(2 * out, np.round(2 * out), atol=1e-5)


def test_diloco_outer_step_matches_manual_nesterov():
    """DiLoCo outer update: pseudo-grad = master − avg; torch-style Nesterov
    SGD (buf = μ·buf + g; update = g + μ·buf) with lr=0.7, μ=0.9
    (reference diloco.py:26-28, 43-49, 62-71), replicated on all nodes."""
    K, H = 2, 2
    w0 = np.full((K, 2), 10.0, np.float32)
    strat = DiLoCoStrategy(optim_spec=OptimSpec("sgd", lr=1.0), H=H)
    rt, step_fn, params, state = make_harness(strat, K, {"w": w0})
    # node k gets grad +1 or -3 → after 2 inner sgd steps: w = 10 - 2*g_k
    g = np.stack([np.full(2, 1.0), np.full(2, -3.0)]).astype(np.float32)
    comm = []
    for t in range(H + 1):
        params, state, m = step_fn(params, state, {"w": g}, t)
        comm.append(float(m["comm_bytes"][0]))
    out = jax.device_get(params)["w"]
    # timeline: t=0 inner (no outer: step>0 false), t=1 inner, outer at
    # t=2 fires AFTER the t=2 inner step. inner steps applied: 3.
    # At outer time: w_k = 10 - 3*g_k → w = [7, 19]; avg = 13.
    # pseudo = master - avg = 10 - 13 = -3
    # buf = 0.9*0 + (-3) = -3 ; nesterov update = -3 + 0.9*(-3) = -5.7
    # master' = 10 - 0.7*(-5.7) = 13.99
    assert comm[0] == 0 and comm[1] == 0 and comm[2] > 0
    np.testing.assert_allclose(out, 13.99, rtol=1e-5)
    # all nodes bit-identical after outer sync
    np.testing.assert_array_equal(out[0], out[1])


def test_sparta_masked_exchange():
    """Masked entries take the node-mean; unmasked entries stay local.
    Mask agreement is by shared PRNG (replaces rank-0 broadcast,
    reference sparta.py:32-42)."""
    K = 4
    n = 1000
    w0 = np.repeat(np.arange(K, dtype=np.float32)[:, None], n, 1)
    strat = SPARTAStrategy(inner_optim=OptimSpec("sgd", lr=0.0),
                           p_sparta=0.3)
    rt, step_fn, params, state = make_harness(strat, K, {"w": w0})
    zero_g = {"w": np.zeros((K, n), np.float32)}
    params, state, m = step_fn(params, state, zero_g, 0)
    out = jax.device_get(params)["w"]
    mean = np.arange(K).mean()
    exchanged = np.isclose(out[0], mean)
    frac = exchanged.mean()
    assert 0.2 < frac < 0.4, frac  # ≈ p_sparta = 0.3
    # same entries exchanged on every node; others untouched
    for k in range(K):
        np.testing.assert_allclose(out[k][exchanged], mean, rtol=1e-6)
        np.testing.assert_allclose(out[k][~exchanged], k)
    assert 0 < float(m["comm_bytes"][0]) < 2 * 4 * n


@pytest.mark.parametrize("selector_cls", [ShuffledSequentialIndexSelector,
                                          PartitionedIndexSelector])
def test_cyclic_selectors_cover_everything_once(selector_cls):
    """Both cyclic selectors partition indices: over one full cycle every
    index is selected exactly once (reference sparta.py:88-193)."""
    sel = selector_cls(p=0.25)
    x = jnp.zeros((7, 13))  # 91 elements, doesn't divide 4
    num_partitions = 4
    total = np.zeros((7, 13), np.int32)
    for it in range(num_partitions):
        m = np.asarray(sel.mask(x, leaf_idx=0, iteration=jnp.asarray(it)))
        total += m.astype(np.int32)
    np.testing.assert_array_equal(total, 1)


def test_random_selector_rate():
    sel = RandomIndexSelector(p=0.1)
    x = jnp.zeros((100, 100))
    m = np.asarray(sel.mask(x, 0, jnp.asarray(3)))
    assert 0.07 < m.mean() < 0.13
    m2 = np.asarray(sel.mask(x, 0, jnp.asarray(4)))
    assert not np.array_equal(m, m2)  # re-randomized per iteration


def test_sparta_diloco_combo_runs():
    """The composition the reference shipped broken (SURVEY §2.1 🟡):
    sparse exchange every step + outer step every H."""
    K, H = 2, 2
    # replicas start identical (the framework invariant the reference
    # establishes by broadcast, train_node.py:101-104) and drift via
    # node-dependent gradients
    w0 = np.full((K, 8), 5.0, np.float32)
    strat = SPARTADiLoCoStrategy(optim_spec=OptimSpec("sgd", lr=0.1),
                                 p_sparta=0.5, H=H)
    rt, step_fn, params, state = make_harness(strat, K, {"w": w0})
    g = np.repeat(np.arange(1, K + 1, dtype=np.float32)[:, None], 8, 1)
    for t in range(H + 1):
        params, state, m = step_fn(params, state, {"w": g}, t)
    out = jax.device_get(params)["w"]
    assert np.all(np.isfinite(out))
    # after the outer step at t=H all nodes are synced to the master
    np.testing.assert_array_equal(out[0], out[1])


def test_zero_reduce_matches_simple_reduce():
    """ZeRO-1 sharding is a memory layout, not an algorithm change: K nodes
    each updating 1/K of the flat parameter vector must produce the same
    params as every node updating all of it. Odd param count exercises the
    zero-padded last shard."""
    K = 4
    rng = np.random.default_rng(0)
    w0 = {"w": np.repeat(rng.normal(size=(1, 7, 3)).astype(np.float32),
                         K, axis=0),
          "b": np.repeat(rng.normal(size=(1, 5)).astype(np.float32),
                         K, axis=0)}

    def run(strat_cls):
        strat = strat_cls(
            optim_spec=OptimSpec("adamw", lr=1e-2, weight_decay=0.1),
            max_norm=1.0,
        )
        rt, step_fn, params, state = make_harness(strat, K, w0)
        for t in range(5):
            g = {"w": rng_g.normal(size=(K, 7, 3)).astype(np.float32),
                 "b": rng_g.normal(size=(K, 5)).astype(np.float32)}
            params, state, m = step_fn(params, state, g, t)
        return jax.device_get(params), jax.device_get(state)

    rng_g = np.random.default_rng(1)
    p_simple, _ = run(SimpleReduceStrategy)
    rng_g = np.random.default_rng(1)
    p_zero, s_zero = run(ZeroReduceStrategy)
    for key in ("w", "b"):
        np.testing.assert_allclose(p_zero[key], p_simple[key],
                                   atol=1e-6, rtol=1e-5)
    # optimizer state really is sharded: Adam moments are flat
    # [K, ceil(26/4)] (leading K = per-node axis of the harness)
    moments = [x for x in jax.tree.leaves(s_zero["opt"]) if x.ndim == 2]
    assert moments and all(x.shape == (K, -(-26 // K)) for x in moments), \
        [x.shape for x in jax.tree.leaves(s_zero["opt"])]


def test_zero_reduce_canonical_matches_vnode_schedule():
    """On a physical node mesh ZeRO-1 runs the canonical reduce-scatter +
    all-gather schedule; under vnode folding it falls back to pmean+slice.
    Same K, same grads → identical parameters (incl. the distributed
    global-norm clip), and comm_bytes reports each schedule's real cost
    ((K−1)/K·(|g|+|θ|) vs (K−1)/K·(2|g|+|θ|))."""
    K = 4
    rng = np.random.default_rng(3)
    w0 = {"w": np.repeat(rng.normal(size=(1, 7, 3)).astype(np.float32),
                         K, axis=0),
          "b": np.repeat(rng.normal(size=(1, 5)).astype(np.float32),
                         K, axis=0)}

    def run(n_devices):
        strat = ZeroReduceStrategy(
            optim_spec=OptimSpec("adamw", lr=1e-2), max_norm=1.0)
        rt, step_fn, params, state = make_harness(
            strat, K, w0, devices=jax.devices()[:n_devices])
        assert (rt.n_virt == 1) == (n_devices == K)
        rng_g = np.random.default_rng(4)
        comm = None
        for t in range(3):
            g = {"w": rng_g.normal(size=(K, 7, 3)).astype(np.float32),
                 "b": rng_g.normal(size=(K, 5)).astype(np.float32)}
            params, state, m = step_fn(params, state, g, t)
            comm = float(np.asarray(m["comm_bytes"]).ravel()[0])
        return jax.device_get(params), comm

    p_can, c_can = run(K)      # n_virt=1 → reduce-scatter
    p_vn, c_vn = run(K // 2)   # n_virt=2 → pmean+slice fallback
    for key in ("w", "b"):
        np.testing.assert_allclose(p_can[key], p_vn[key],
                                   atol=1e-6, rtol=1e-5)
    bytes_gp = (7 * 3 + 5) * 4  # |g| = |θ| = 26 f32 leaves per node
    np.testing.assert_allclose(c_can, 0.75 * 2 * bytes_gp)
    np.testing.assert_allclose(c_vn, 0.75 * 3 * bytes_gp)


def test_zero_reduce_requires_ctx():
    strat = ZeroReduceStrategy(optim_spec=OptimSpec("sgd", lr=0.1))
    strat.finalize(10)
    from gym_tpu.strategy.base import StrategyLifecycleError
    with pytest.raises(StrategyLifecycleError, match="bind_ctx"):
        strat.init({"w": jnp.zeros((4,))})


def test_diloco_shard_outer_matches_replicated():
    """shard_outer=True (1/K master + momentum slices, ZeRO on the outer
    optimizer) must reproduce the replicated outer step exactly: the outer
    input is node-identical, so slicing commutes with elementwise
    Nesterov. Odd param count exercises the padded last shard."""
    K, H = 4, 2
    rng = np.random.default_rng(9)
    w0 = {"w": np.repeat(rng.normal(size=(1, 7, 3)).astype(np.float32),
                         K, axis=0),
          "b": np.repeat(rng.normal(size=(1, 5)).astype(np.float32),
                         K, axis=0)}

    def run(shard_outer):
        strat = DiLoCoStrategy(optim_spec=OptimSpec("sgd", lr=0.05), H=H,
                               shard_outer=shard_outer)
        rt, step_fn, params, state = make_harness(strat, K, w0)
        g = np.random.default_rng(10)
        for t in range(2 * H + 1):
            grads = {"w": g.normal(size=(K, 7, 3)).astype(np.float32),
                     "b": g.normal(size=(K, 5)).astype(np.float32)}
            params, state, m = step_fn(params, state, grads, t)
        return jax.device_get(params), float(m["comm_bytes"][0])

    p_rep, comm_rep = run(False)
    p_sh, comm_sh = run(True)
    for key in ("w", "b"):
        np.testing.assert_allclose(p_sh[key], p_rep[key],
                                   atol=1e-6, rtol=1e-5)
    # the sharded outer round pays the extra all_gather:
    # 3(K-1)/K·|θ| vs the replicated 2(K-1)/K·|θ| (26 f32 params = 104 B)
    assert comm_rep == 2.0 * 3 / 4 * 104
    assert comm_sh == 3.0 * 3 / 4 * 104


def test_noloco_gossip_preserves_node_mean_and_matches_host_twin():
    """One NoLoCo gossip round with a pass-through outer step (SGD
    lr=1.0, no momentum): params_i ← (p_i + p_σ(i))/2 with σ the host
    twin's permutation, so the NODE-MEAN of the params is preserved
    exactly (doubly-stochastic mixing) while nodes move toward pairwise
    consensus — and zero inner lr isolates the gossip itself."""
    from gym_tpu.strategy import NoLoCoStrategy

    K, H = 4, 2
    rng = np.random.default_rng(11)
    w0 = {"w": rng.normal(size=(K, 5)).astype(np.float32)}
    zeros = {"w": np.zeros((K, 5), np.float32)}
    strat = NoLoCoStrategy(
        optim_spec=OptimSpec("sgd", lr=0.0),
        outer_optim_spec=OptimSpec("sgd", lr=1.0, momentum=0.0,
                                   nesterov=False),
        H=H)
    rt, step_fn, params, state = make_harness(strat, K, w0)
    before = jax.device_get(params)["w"].copy()

    params, state, m = step_fn(params, state, zeros, 1)   # off-cadence
    np.testing.assert_allclose(jax.device_get(params)["w"], before,
                               atol=1e-7)
    assert np.all(m["comm_bytes"] == 0.0)

    params, state, m = step_fn(params, state, zeros, H)   # gossip round
    after = jax.device_get(params)["w"]
    sigma = strat.partner_permutation(H, K)
    assert sorted(sigma) == list(range(K))
    assert np.all(sigma != np.arange(K))                  # derangement
    for i in range(K):
        np.testing.assert_allclose(
            after[i], 0.5 * (before[i] + before[sigma[i]]),
            atol=1e-6, rtol=1e-5)
    # doubly-stochastic mixing: the fleet mean is invariant
    np.testing.assert_allclose(after.mean(axis=0), before.mean(axis=0),
                               atol=1e-6, rtol=1e-5)
    # p2p accounting: |θ| per node (5 f32 = 20 B), NOT 2(K−1)/K·|θ|
    assert np.all(m["comm_bytes"] == 20.0)


def test_noloco_consensus_emerges_over_rounds():
    """Repeated partner averaging with fresh random cycles contracts the
    node spread: after a few rounds every node is near the (preserved)
    fleet mean even though no global collective ever ran."""
    from gym_tpu.strategy import NoLoCoStrategy

    K = 8
    rng = np.random.default_rng(12)
    w0 = {"w": rng.normal(size=(K, 3)).astype(np.float32)}
    zeros = {"w": np.zeros((K, 3), np.float32)}
    strat = NoLoCoStrategy(
        optim_spec=OptimSpec("sgd", lr=0.0),
        outer_optim_spec=OptimSpec("sgd", lr=1.0, momentum=0.0,
                                   nesterov=False),
        H=1)
    rt, step_fn, params, state = make_harness(strat, K, w0)
    spread0 = jax.device_get(params)["w"].std(axis=0).max()
    for t in range(1, 13):
        params, state, _ = step_fn(params, state, zeros, t)
    after = jax.device_get(params)["w"]
    np.testing.assert_allclose(after.mean(axis=0),
                               w0["w"].mean(axis=0), atol=1e-5)
    assert after.std(axis=0).max() < 0.05 * spread0


def test_dynamiq_canonical_matches_vnode_schedule():
    """DynamiQ's two emulation schedules (psum_scatter + all_gather on a
    pure node mesh; pmean + slice under vnode folding) apply the SAME
    shared-PRNG codec noise to the same values — identical params, and
    the comm_bytes metric reports the CANONICAL compressed wire cost
    either way."""
    from gym_tpu.strategy import DynamiQStrategy

    K = 4
    rng = np.random.default_rng(13)
    w0 = {"w": np.repeat(rng.normal(size=(1, 7, 3)).astype(np.float32),
                         K, axis=0),
          "b": np.repeat(rng.normal(size=(1, 5)).astype(np.float32),
                         K, axis=0)}

    def run(n_devices):
        strat = DynamiQStrategy(optim_spec=OptimSpec("adamw", lr=1e-2),
                                codec="int8", tile=16)
        rt, step_fn, params, state = make_harness(
            strat, K, w0, devices=jax.devices()[:n_devices])
        assert (rt.n_virt == 1) == (n_devices == K)
        rng_g = np.random.default_rng(14)
        comm = None
        for t in range(3):
            g = {"w": rng_g.normal(size=(K, 7, 3)).astype(np.float32),
                 "b": rng_g.normal(size=(K, 5)).astype(np.float32)}
            params, state, m = step_fn(params, state, g, t)
            comm = float(np.asarray(m["comm_bytes"]).ravel()[0])
        return jax.device_get(params), strat, comm

    p_can, strat, c_can = run(K)      # n_virt=1 → reduce-scatter
    p_vn, _, c_vn = run(K // 2)       # n_virt=2 → pmean+slice fallback
    for key in ("w", "b"):
        np.testing.assert_allclose(p_can[key], p_vn[key],
                                   atol=1e-6, rtol=1e-5)
    # both account the canonical compressed schedule: (K−1)/K·(w1+w2)
    w1, w2 = strat._wires(26, K)
    assert c_can == c_vn == pytest.approx(3 / 4 * (w1 + w2))


def test_dynamiq_quantized_step_approximates_dense_allreduce():
    """int8 stochastic rounding perturbs the gradient by at most one
    quantization bin per hop: a DynamiQ step must land within a few bins
    of the exact SimpleReduce step on the same grads (and K=1 must be
    EXACTLY the dense update — nothing on the wire, nothing to
    compress)."""
    from gym_tpu.strategy import DynamiQStrategy

    K = 4
    w0 = {"w": np.zeros((K, 40), np.float32)}
    rng = np.random.default_rng(15)
    g = {"w": np.repeat(rng.normal(size=(1, 40)).astype(np.float32),
                        K, axis=0)}

    def run(strat_cls, **kw):
        strat = strat_cls(optim_spec=OptimSpec("sgd", lr=1.0), **kw)
        rt, step_fn, params, state = make_harness(strat, K, w0)
        params, state, m = step_fn(params, state, g, 0)
        return jax.device_get(params)["w"]

    p_dense = run(SimpleReduceStrategy)
    p_q = run(DynamiQStrategy, codec="int8", tile=64)
    bin_size = np.abs(g["w"][0]).max() / 127
    assert np.abs(p_q - p_dense).max() <= 2.5 * bin_size
    # node-identical output: every node decompresses the same payloads
    for k in range(1, K):
        np.testing.assert_array_equal(p_q[k], p_q[0])

    # K=1: bit-exact dense update
    w1 = {"w": np.zeros((1, 40), np.float32)}
    g1 = {"w": g["w"][:1]}
    strat = DynamiQStrategy(optim_spec=OptimSpec("sgd", lr=1.0),
                            codec="int8")
    rt, step_fn, params, state = make_harness(strat, 1, w1)
    params, state, m = step_fn(params, state, g1, 0)
    np.testing.assert_array_equal(jax.device_get(params)["w"],
                                  -g1["w"])
    assert np.all(m["comm_bytes"] == 0.0)


def test_dynamiq_error_feedback_conserves_dropped_mass_exactly():
    """Top-k with double error feedback: nothing is ever lost — summing
    the delivered updates of a constant gradient g over T steps gives
    EXACTLY T·g minus what the residuals still hold (hop 1: mean over
    nodes; hop 2: each node's own-chunk residual), the EF-SGD
    conservation law. SGD lr=1 makes the delivered sum directly
    observable as −params."""
    from gym_tpu.strategy import DynamiQStrategy

    K, n = 4, 40
    shard = n // K
    w0 = {"w": np.zeros((K, n), np.float32)}
    rng = np.random.default_rng(16)
    g = {"w": np.repeat(rng.normal(size=(1, n)).astype(np.float32),
                        K, axis=0)}
    strat = DynamiQStrategy(optim_spec=OptimSpec("sgd", lr=1.0),
                            codec="topk", frac=0.1)
    rt, step_fn, params, state = make_harness(strat, K, w0)
    T = 12
    for t in range(T):
        params, state, m = step_fn(params, state, g, t)
    final = jax.device_get(params)["w"]
    st = jax.device_get(state)
    # the residuals really are training state, carried across steps
    assert st["residual"].shape == (K, n) and np.any(st["residual"] != 0)
    assert st["residual2"].shape == (K, shard)
    # conservation: delivered = T·g − mean_i r_i − r2[chunk owner]
    # (node j owns chunk j, so row j of residual2 assembles in order)
    undelivered = (st["residual"].mean(axis=0)
                   + st["residual2"].reshape(-1))
    np.testing.assert_allclose(-final[0], T * g["w"][0] - undelivered,
                               rtol=1e-4, atol=1e-4)
    # and the delivered sum is genuinely converging on T·g: the lag is
    # bounded by what the residuals hold, not growing with T
    assert np.abs(undelivered).max() < T * np.abs(g["w"][0]).max()
    # all nodes decompress the same gathered payloads → identical params
    for k in range(1, K):
        np.testing.assert_array_equal(final[k], final[0])


# -- compressed outer loops (ISSUE 12: CompressedLink × strategy) ----------


def test_compressed_diloco_outer_round_within_bins_of_dense():
    """One int8 outer round must land within a few quantization bins of
    the dense DiLoCo round on the same grads (the delta is what's
    compressed, so the bin is amax(delta)/127 per tile), and the
    replicas stay bit-identical (the pmean reconstruction is a
    collective)."""
    K, H = 4, 2
    w0 = {"w": np.full((K, 64), 10.0, np.float32)}
    g = np.repeat(np.linspace(-3, 1, K, dtype=np.float32)[:, None], 64, 1)

    def run(**kw):
        strat = DiLoCoStrategy(optim_spec=OptimSpec("sgd", lr=1.0), H=H,
                               **kw)
        rt, step_fn, params, state = make_harness(strat, K, dict(w0))
        for t in range(H + 1):
            params, state, m = step_fn(params, state, {"w": g}, t)
        return jax.device_get(params)["w"], jax.device_get(state), m

    p_dense, _, _ = run()
    p_q, st_q, m = run(codec="int8", tile=64)
    # per-node delta after 3 inner steps is 3·g_k; bins per node ≤
    # amax(3·g)/127; the averaged reconstruction error is within a few
    # bins through the outer Nesterov step (factor 1.9 = 1+momentum)
    bin_size = 3 * np.abs(g).max() / 127
    assert np.abs(p_q - p_dense).max() <= 3 * 1.9 * bin_size
    for k in range(1, K):
        np.testing.assert_array_equal(p_q[k], p_q[0])
    # the residual is genuine training state on every node
    res = st_q["modules"][0]["ef_residual"]
    assert res.shape == (K, 64) and np.any(res != 0)
    # metric = the declared compressed wire cost
    from gym_tpu.strategy import CompressedLink
    wire = CompressedLink("int8", tile=64).wire_bytes(64)
    assert np.all(m["comm_bytes"] == pytest.approx(2 * 3 / 4 * wire))


def test_compressed_diloco_error_feedback_conserves_dropped_mass():
    """The EF conservation law at the strategy level, deterministic:
    with a top-k link and a pass-through outer step (SGD lr=1, no
    momentum, so ``master <- master + mean(delta_hat)`` is directly
    observable), NOTHING is ever lost: after T steps
    ``master == total_true_delta - mean_i(residual_i)`` exactly. The
    ablated link (error_feedback=False) permanently drops every
    never-selected coordinate; its master provably violates the
    conservation that the residual restores."""
    K, H, n = 2, 1, 50
    w0 = {"w": np.zeros((K, n), np.float32)}
    # one tiny coordinate (index 0), the rest large: frac=0.1 keeps 5
    g_row = np.r_[0.01, np.linspace(1, 2, n - 1)].astype(np.float32)
    g = {"w": np.repeat(g_row[None], K, 0)}
    T = 12   # steps; rounds fire at t=1..11 (H=1, step>0 gate)

    def run(error_feedback):
        strat = DiLoCoStrategy(
            optim_spec=OptimSpec("sgd", lr=1.0),
            outer_optim_spec=OptimSpec("sgd", lr=1.0, momentum=0.0,
                                       nesterov=False),
            H=H, codec="topk", frac=0.1, error_feedback=error_feedback)
        rt, step_fn, params, state = make_harness(strat, K, dict(w0))
        for t in range(T):
            params, state, _ = step_fn(params, state, g, t)
        return (jax.device_get(params)["w"],
                jax.device_get(state)["modules"][0])

    p_ef, ms = run(True)
    p_ablate, ms_ablate = run(False)
    # total true delta fed into the link per node: 2 inner steps before
    # the first round, then 1 per round -> -T*g in total
    total = -T * g_row
    # conservation: master == total - mean_i(residual_i), exactly
    undelivered = ms["ef_residual"].mean(axis=0)
    np.testing.assert_allclose(p_ef[0], total - undelivered,
                               rtol=1e-4, atol=1e-5)
    # the ablated link has no residual, and the dropped coordinate's
    # mass (~ -0.12 here) is gone for good: nothing accounts for it
    assert "ef_residual" not in ms_ablate
    assert p_ablate[0][0] == 0.0
    assert abs(p_ablate[0][0] - total[0]) > 0.1
    # the EF residual is exactly where coordinate 0's mass lives
    assert abs(undelivered[0] - total[0]) < 1e-5
    # both runs deliver the large coordinates
    assert p_ef[0][-1] < -10 and p_ablate[0][-1] < -10


def test_compressed_noloco_gossip_within_bins_and_deterministic():
    """Compressed gossip: avg_i = (p_i + p̂_σ(i))/2 with p̂ the partner's
    int8 reconstruction — within one bin of the dense gossip — and the
    whole exchange is bit-reproducible across runs (link keys are pure
    functions of (seed, step, node)), with the two partners of a pair
    drawing DIFFERENT rounding noise."""
    from gym_tpu.strategy import NoLoCoStrategy

    K, H, n = 4, 2, 64
    rng = np.random.default_rng(21)
    w0 = {"w": rng.normal(size=(K, n)).astype(np.float32)}
    zeros = {"w": np.zeros((K, n), np.float32)}

    def run(codec=None, **kw):
        strat = NoLoCoStrategy(
            optim_spec=OptimSpec("sgd", lr=0.0),
            outer_optim_spec=OptimSpec("sgd", lr=1.0, momentum=0.0,
                                       nesterov=False),
            H=H, codec=codec, **kw)
        rt, step_fn, params, state = make_harness(strat, K, dict(w0))
        params, state, m = step_fn(params, state, zeros, H)
        return jax.device_get(params)["w"], m, strat

    dense, _, _ = run()
    q1, m, strat = run(codec="int8", tile=n)
    q2, _, _ = run(codec="int8", tile=n)
    np.testing.assert_array_equal(q1, q2)          # bit-reproducible
    bin_size = np.abs(w0["w"]).max() / 127
    # only the partner half is quantized → error ≤ bin/2 per element
    assert np.abs(q1 - dense).max() <= bin_size
    sigma = strat.partner_permutation(H, K)
    # partner i's contribution was quantized with node σ(i)'s key; own
    # half is lossless: avg − p_i/2 differs from p_σ(i)/2 by the
    # partner's rounding noise, which differs BETWEEN partners
    noise = [q1[i] - 0.5 * (w0["w"][i] + w0["w"][sigma[i]])
             for i in range(K)]
    assert any(not np.array_equal(noise[0], nz) for nz in noise[1:])
    # p2p accounting: the codec's wire bytes, not |θ|
    from gym_tpu.strategy import CompressedLink
    wire = CompressedLink("int8", tile=n).wire_bytes(n)
    assert np.all(m["comm_bytes"] == wire)
    assert wire < 4.0 * n


def test_noloco_partner_permutation_odd_and_non_power_of_two():
    """ISSUE 12 satellite: the shared-PRNG partner draw at K = 3, 5, 6.
    A perfect pairing (involution) cannot exist for odd K; the module's
    documented design is a random K-CYCLE — always fixed-point-free, so
    every node still sends exactly once and receives exactly once — and
    the byte accounting (|θ| per node, pairs a permutation) must hold at
    every K, matching the jitted draw."""
    from gym_tpu.strategy import NoLoCoStrategy

    PARAMS = {"w": jax.ShapeDtypeStruct((40,), np.float32)}
    s = NoLoCoStrategy(H=2)
    for K in (3, 5, 6):
        for step in (2, 4, 8):
            sigma = s.partner_permutation(step, K)
            assert sorted(sigma) == list(range(K)), (K, step, sigma)
            assert np.all(sigma != np.arange(K)), (K, step, sigma)
            # the host twin IS the jitted draw
            jitted = np.asarray(jax.jit(
                lambda st, k=K: s._perm_jax(st, k)
            )(jnp.asarray(step, jnp.int32)))
            np.testing.assert_array_equal(sigma, jitted)
            evs = s.comm_events(step, PARAMS, K)
            assert len(evs) == 1 and evs[0].op == "p2p"
            # every node transmits exactly |θ| = 160 B
            assert evs[0].per_node_tx() == 160.0
            srcs = sorted(i for i, _ in evs[0].pairs)
            dsts = sorted(j for _, j in evs[0].pairs)
            assert srcs == dsts == list(range(K))
    # and the jitted step at an ODD node count reports the same metric
    K = 3
    strat = NoLoCoStrategy(optim_spec=OptimSpec("sgd", lr=0.0), H=2)
    w0 = {"w": np.random.default_rng(0).normal(
        size=(K, 40)).astype(np.float32)}
    rt, step_fn, params, state = make_harness(strat, K, w0)
    params, state, m = step_fn(params, state,
                               {"w": np.zeros((K, 40), np.float32)}, 2)
    assert np.all(m["comm_bytes"] == 160.0)


def test_demo_outer_dense_limit_is_parameter_averaging():
    """Decoupled momentum sanity oracle: replicas start identical (the
    framework invariant) and drift via per-node gradients; with the
    dense identity link, beta=0 and outer_lr=1, one sync is EXACTLY
    parameter averaging (master <- master + mean(drift_i) =
    mean(params_i)) -- and with a top-k link the masters stay
    node-identical while the momentum buffers keep the undelivered
    remainder."""
    from gym_tpu.strategy import DecoupledMomentumStrategy

    K, H, n = 4, 2, 30
    rng = np.random.default_rng(23)
    w0 = {"w": np.repeat(rng.normal(size=(1, n)).astype(np.float32),
                         K, 0)}
    # per-node drift: inner SGD lr=1 moves node k by -g_k per step
    g = {"w": rng.normal(size=(K, n)).astype(np.float32)}

    def run(**kw):
        strat = DecoupledMomentumStrategy(
            optim_spec=OptimSpec("sgd", lr=1.0), H=H, **kw)
        rt, step_fn, params, state = make_harness(strat, K, dict(w0))
        for t in range(H + 1):
            params, state, m = step_fn(params, state, g, t)
        return (jax.device_get(params)["w"], jax.device_get(state), m)

    p, st, m = run(codec=None, outer_lr=1.0, outer_momentum=0.0)
    # 3 inner steps before the sync at t=2: params_k = w0 - 3*g_k
    mean = (w0["w"] - 3 * g["w"]).mean(axis=0)
    for k in range(K):
        np.testing.assert_allclose(p[k], mean, atol=1e-5, rtol=1e-5)
    # dense link: everything delivered, momentum fully decoupled to 0
    np.testing.assert_allclose(st["modules"][0]["momentum"], 0.0,
                               atol=1e-6)

    p_t, st_t, m_t = run(codec="topk", frac=0.2, outer_lr=1.0,
                         outer_momentum=0.0)
    for k in range(1, K):
        np.testing.assert_array_equal(p_t[k], p_t[0])
    mom = st_t["modules"][0]["momentum"]
    assert np.any(mom != 0)          # the slow mass stayed local
    # comm: the compressed all-reduce convention over the wire bytes
    from gym_tpu.strategy import CompressedLink
    wire = CompressedLink("topk", frac=0.2).wire_bytes(n)
    assert np.all(m_t["comm_bytes"] == pytest.approx(3 / 4 * 2 * wire))
    assert np.all(m["comm_bytes"] == pytest.approx(3 / 4 * 2 * 4.0 * n))


def test_compressed_link_rejects_incoherent_compositions():
    """codec × shard_outer and codec × participation<1 are physically
    incoherent (sharded/frozen residuals) — typed rejections, not silent
    misbehavior."""
    with pytest.raises(ValueError, match="shard_outer"):
        DiLoCoStrategy(H=2, codec="int8", shard_outer=True)
    with pytest.raises(ValueError, match="participation"):
        DiLoCoStrategy(H=2, codec="int8", participation=0.5)
