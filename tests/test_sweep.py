"""Sweep runner (gym_tpu.sim.sweep): grid construction, end-to-end smoke,
cross-invocation resume, and the per-cell run-dir regression (same-named
CSVLogger runs clobber each other's output)."""

import csv
import json
import os

import pytest

from gym_tpu.sim.sweep import (Cell, SweepConfig, _invalidate_if_stale,
                               _workload_sig, grid, run_sweep)


def _cfg(tmp_path, **kw):
    base = dict(
        strategies=["diloco", "simple_reduce"],
        presets=["wan", "datacenter"],
        nodes=[2],
        H=[4],
        steps=6,
        batch_size=4,
        block_size=32,
        n_layer=1,
        n_head=1,
        n_embd=32,
        out=str(tmp_path / "sweep"),
    )
    base.update(kw)
    return SweepConfig(**base)


def test_grid_dedupes_h_for_interval_free_strategies(tmp_path):
    cfg = _cfg(tmp_path, H=[4, 8])
    cells = grid(cfg)
    # diloco × 2 H values, simple_reduce once, per preset
    assert len(cells) == 2 * (2 + 1)
    assert Cell("simple_reduce", None, 2, "wan") in cells
    assert Cell("diloco", 8, 2, "datacenter") in cells
    with pytest.raises(ValueError, match="unknown strategy"):
        _cfg(tmp_path, strategies=["gossipmax"])
    # aliases normalize
    assert _cfg(tmp_path, strategies=["base", "zero"]).strategies \
        == ["simple_reduce", "zero_reduce"]


def test_workload_change_invalidates_cached_cells(tmp_path):
    """Cell results are only valid under the workload that measured
    them: a rerun with e.g. --steps 100 against an out dir holding
    30-step results must discard the cache (cells, checkpoints, logs),
    not silently report the stale rows as the new config's."""
    out = str(tmp_path / "out")
    sig30 = _workload_sig(_cfg(tmp_path, out=out, steps=30))
    assert not _invalidate_if_stale(out, sig30)   # fresh dir: no wipe
    for sub in ("cells", "ckpt", "logs"):
        os.makedirs(os.path.join(out, sub), exist_ok=True)
        with open(os.path.join(out, sub, "stale.marker"), "w") as f:
            f.write("x")
    assert not _invalidate_if_stale(out, sig30)   # same sig: kept
    assert os.path.exists(os.path.join(out, "cells", "stale.marker"))
    sig100 = _workload_sig(_cfg(tmp_path, out=out, steps=100))
    assert _invalidate_if_stale(out, sig100)      # changed sig: wiped
    for sub in ("cells", "ckpt", "logs"):
        assert not os.path.exists(os.path.join(out, sub, "stale.marker"))
    assert not _invalidate_if_stale(out, sig100)  # new marker persisted


def test_sweep_end_to_end_and_resume(tmp_path):
    cfg = _cfg(tmp_path)
    rows = run_sweep(cfg)
    assert len(rows) == 4

    # per-cell run dirs (the CSVLogger collision regression): every cell
    # has its OWN logs dir whose train.csv holds all `steps` rows — with
    # a shared run name the later cells would have clobbered the earlier
    # ones' files
    run_dirs = set()
    for r in rows:
        d = os.path.join(cfg.out, "logs", r["cell"])
        run_dirs.add(d)
        with open(os.path.join(d, "train.csv"), newline="") as f:
            got = list(csv.reader(f))
        assert len(got) == cfg.steps + 1, r["cell"]
        assert got[0][-1] == "sim_step_s"
    assert len(run_dirs) == 4

    # every trace reconciles with its logged cum_comm_bytes
    assert all(r["reconciled"] for r in rows), rows

    # the motivating comparison: DiLoCo beats AllReduce on WAN. At this
    # smoke scale the per-cell MEASURED compute is 2-core-box noise that
    # can swamp the comm delta, so compare the deterministic modeled
    # comm, and the totals under a COMMON compute rate (total ordering
    # at any shared rate == comm ordering; the 30-step acceptance sweep
    # is where comm dominates the raw totals too)
    by = {(r["strategy"], r["topology"]): r for r in rows}
    d, a = by[("diloco", "wan")], by[("simple_reduce", "wan")]
    assert d["sim_comm_s"] < a["sim_comm_s"] / 2
    common = min(d["compute_s_per_step"], a["compute_s_per_step"])
    assert d["sim_comm_s"] + cfg.steps * common \
        < a["sim_comm_s"] + cfg.steps * common

    # artifacts
    assert os.path.exists(os.path.join(cfg.out, "results.csv"))
    with open(os.path.join(cfg.out, "results.json")) as f:
        assert len(json.load(f)["rows"]) == 4
    with open(os.path.join(cfg.out, "report.md")) as f:
        report = f.read()
    assert "Headline: DiLoCo" in report
    assert "reconcile" in report

    # resume: a second invocation re-runs NOTHING (cell files are the
    # completion markers) and reproduces identical rows
    marker = os.path.join(cfg.out, "cells", rows[0]["cell"] + ".json")
    mtime = os.path.getmtime(marker)
    rows2 = run_sweep(cfg)
    assert rows2 == rows
    assert os.path.getmtime(marker) == mtime

    # extending the grid only runs the new cells
    cfg3 = _cfg(tmp_path, strategies=["diloco", "simple_reduce", "fedavg"])
    rows3 = run_sweep(cfg3)
    assert len(rows3) == 6
    assert os.path.getmtime(marker) == mtime
    assert {r["strategy"] for r in rows3} \
        == {"diloco", "simple_reduce", "fedavg"}


def test_grid_bits_axis_multiplies_only_compressed_strategies(tmp_path):
    cfg = _cfg(tmp_path, strategies=["dynamiq", "noloco", "simple_reduce"],
               presets=["wan"], H=[4, 8], bits=[8, 4])
    cells = grid(cfg)
    # dynamiq × 2 bits, noloco × 2 H (default codecs = [dense]),
    # simple_reduce once
    assert len(cells) == 2 + 2 + 1
    assert Cell("dynamiq", None, 2, "wan", "int8") in cells
    assert Cell("dynamiq", None, 2, "wan", "int4") in cells
    assert Cell("noloco", 4, 2, "wan") in cells
    assert Cell("dynamiq", None, 2, "wan", "int8").cell_id \
        == "dynamiq_int8_n2_wan"
    assert Cell("dynamiq", None, 2, "wan", "int8").bits == 8
    assert Cell("noloco", 4, 2, "wan").cell_id == "noloco_H4_n2_wan"
    # the headline alias resolves AND pins its named codec — --bits
    # cannot silently override what the alias says
    cfg8 = _cfg(tmp_path, strategies=["dynamiq_int8"], presets=["wan"],
                bits=[4])
    assert cfg8.strategies == ["dynamiq"]
    assert [c.codec for c in grid(cfg8)] == ["int8"]
    # a cell requested both ways runs once
    cfg_dup = _cfg(tmp_path, strategies=["dynamiq", "dynamiq_int8"],
                   presets=["wan"], bits=[8])
    assert len(grid(cfg_dup)) == 1
    with pytest.raises(ValueError, match="unknown bit-width"):
        _cfg(tmp_path, bits=[16])


def test_grid_codec_axis_multiplies_the_link_family(tmp_path):
    """The ISSUE 12 axis: --codecs multiplies the CompressedLink family
    (diloco/noloco/demo_outer, incl. the dense identity cell), feeds its
    non-dense entries to dynamiq too, and leaves the codec-free
    strategies alone."""
    cfg = _cfg(tmp_path,
               strategies=["diloco", "noloco", "demo_outer", "dynamiq",
                           "simple_reduce"],
               presets=["wan"], H=[4], codecs=["dense", "int4", "topk"])
    cells = grid(cfg)
    # 3 link strategies × 3 codecs + dynamiq × (int8 from --bits +
    # int4/topk from --codecs) + simple_reduce once
    assert len(cells) == 3 * 3 + 3 + 1
    assert Cell("diloco", 4, 2, "wan") in cells            # dense
    assert Cell("diloco", 4, 2, "wan", "int4") in cells
    assert Cell("noloco", 4, 2, "wan", "topk") in cells
    assert Cell("demo_outer", 4, 2, "wan", "int4") in cells
    assert Cell("dynamiq", None, 2, "wan", "topk") in cells
    assert Cell("noloco", 4, 2, "wan", "int4").cell_id \
        == "noloco_H4_int4_n2_wan"
    # dynamiq never gets a dense cell (that's simple_reduce)
    assert Cell("dynamiq", None, 2, "wan", None) not in cells
    with pytest.raises(ValueError, match="unknown codec"):
        _cfg(tmp_path, codecs=["zfp"])


def test_pareto_frontier_verdicts_and_csv(tmp_path):
    """The frontier artifact: dominated configs are OFF, the loss/time
    trade survives (a slower-but-better-loss config stays ON), and
    frontier.csv carries one verdict row per cell."""
    from gym_tpu.sim.sweep import pareto_frontier, write_frontier_csv

    def row(cfg_name, t, loss, **kw):
        r = {"strategy": cfg_name, "H": None, "bits": None,
             "topology": "wan", "nodes": 4, "sim_total_s": t,
             "sim_comm_s": t / 2, "final_train_loss": loss,
             "cum_comm_bytes": 1e6}
        r.update(kw)
        return r

    fast_bad = row("noloco", 1.0, 3.0)
    slow_good = row("simple_reduce", 10.0, 2.0)
    mid_dominated = row("fedavg", 10.0, 3.0)     # worse than both axes
    mid_ok = row("dynamiq", 5.0, 2.5, bits=8)
    diverged = row("sparta", 0.5, float("nan"))  # fastest but NaN loss
    rows = [slow_good, fast_bad, mid_dominated, mid_ok, diverged]
    front = pareto_frontier(rows)
    assert [r["strategy"] for r in front] \
        == ["noloco", "dynamiq", "simple_reduce"]   # sorted by time
    assert mid_dominated not in front
    # a diverged cell is never "Pareto-optimal" (NaN compares False
    # against everything and would otherwise be undominatable)
    assert diverged not in front

    path = str(tmp_path / "frontier.csv")
    write_frontier_csv(path, rows)
    with open(path, newline="") as f:
        got = {r["config"]: r for r in csv.DictReader(f)}
    assert len(got) == 5
    assert got["fedavg"]["on_frontier"] == "False"
    assert got["sparta"]["on_frontier"] == "False"   # diverged
    assert got["dynamiq int8"]["on_frontier"] == "True"
    assert float(got["noloco"]["sim_total_s"]) == 1.0


def test_sweep_with_low_comm_strategies_end_to_end(tmp_path):
    """noloco + dynamiq through the full sweep runner: cells run,
    reconcile at runtime, and the report + frontier artifacts include
    them."""
    cfg = _cfg(tmp_path, strategies=["noloco", "dynamiq_int8"],
               presets=["wan"], H=[3])
    rows = run_sweep(cfg)
    assert len(rows) == 2
    assert all(r["reconciled"] for r in rows), rows
    by = {r["strategy"]: r for r in rows}
    assert by["dynamiq"]["bits"] == 8
    assert by["noloco"]["H"] == 3
    # gossip's per-node traffic is below the compressed all-reduce's
    assert by["noloco"]["cum_comm_bytes"] < by["dynamiq"]["cum_comm_bytes"]
    with open(os.path.join(cfg.out, "frontier.csv"), newline="") as f:
        verdicts = list(csv.DictReader(f))
    assert {v["config"] for v in verdicts} == {"noloco H=3", "dynamiq int8"}
    with open(os.path.join(cfg.out, "report.md")) as f:
        report = f.read()
    assert "Pareto frontier" in report
