"""nanoGPT model-family and GPT-data-pipeline tests (reference
``example/nanogpt/`` parity: config size map, tying, init scheme, loss
contract, crop, generate, dataset classes)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gym_tpu.data import (ContiguousGPTTrainDataset,
                          LazyNonContiguousGPTTrainDataset,
                          NonContiguousGPTTrainDataset, build_dataset_owt,
                          build_dataset_small, char_vocab_size, get_dataset)
from gym_tpu.models import GPT, GPTConfig, crop_block_size, generate, \
    num_params
from gym_tpu.models.nanogpt import decay_mask


def tiny_cfg(**kw):
    base = dict(block_size=32, vocab_size=66, n_layer=2, n_head=2,
                n_embd=32, dropout=0.1, bias=True)
    base.update(kw)
    return GPTConfig(**base)


def test_config_size_map():
    small = GPTConfig.gpt2_size_map("small")
    assert (small.n_layer, small.n_head, small.n_embd) == (4, 4, 128)
    base = GPTConfig.gpt2_size_map("base")
    assert (base.n_layer, base.n_head, base.n_embd) == (12, 12, 768)
    xl = GPTConfig.gpt2_size_map("xl")
    assert (xl.n_layer, xl.n_head, xl.n_embd) == (48, 25, 1600)


def test_forward_loss_and_logits():
    cfg = tiny_cfg()
    model = GPT(cfg)
    idx = np.random.default_rng(0).integers(0, 66, (2, 16)).astype(np.int32)
    tgt = np.roll(idx, -1, axis=1)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        (idx, tgt), train=False,
    )
    loss = model.apply(variables, (idx, tgt), train=False)
    assert loss.shape == ()
    assert np.isfinite(float(loss))
    # untrained loss ≈ ln(vocab)
    assert abs(float(loss) - np.log(66)) < 1.0
    logits = model.apply(variables, idx, train=False)
    assert logits.shape == (2, 16, 66)
    # ignore_index=-1 semantics
    tgt_ig = tgt.copy()
    tgt_ig[:, 8:] = -1
    loss_ig = model.apply(variables, (idx, tgt_ig), train=False)
    assert np.isfinite(float(loss_ig))


def test_weight_tying_and_init_scale():
    cfg = tiny_cfg(dropout=0.0)
    model = GPT(cfg)
    idx = np.zeros((1, 8), np.int32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, idx,
                           train=False)
    params = variables["params"]
    # tying: there is no separate lm_head kernel
    assert "lm_head" not in params
    # scaled residual init: c_proj std ≈ 0.02/sqrt(2*n_layer)
    cp = np.asarray(params["h_0"]["attn"]["c_proj"]["kernel"])
    assert 0.3 * 0.02 < cp.std() < 1.2 * 0.02 / np.sqrt(2 * cfg.n_layer) * 2
    # wte/wpe std ≈ 0.02
    assert abs(np.asarray(params["wte"]["embedding"]).std() - 0.02) < 0.005


def test_num_params_and_crop_and_decay_mask():
    cfg = tiny_cfg(dropout=0.0)
    model = GPT(cfg)
    idx = np.zeros((1, 8), np.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, idx,
                        train=False)["params"]
    n = num_params(params)
    assert n > 0
    new_params, new_cfg = crop_block_size(params, cfg, 16)
    assert new_cfg.block_size == 16
    assert new_params["wpe"]["embedding"].shape[0] == 16
    out = GPT(new_cfg).apply({"params": new_params},
                             np.zeros((1, 16), np.int32), train=False)
    assert out.shape == (1, 16, 66)
    mask = decay_mask(params)
    assert mask["wte"]["embedding"] is True
    assert mask["ln_f"]["scale"] is False
    assert mask["h_0"]["attn"]["c_attn"]["bias"] is False


@pytest.mark.slow
def test_generate():
    cfg = tiny_cfg(dropout=0.0)
    model = GPT(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int32), train=False)["params"]
    out = generate(params, cfg, np.zeros((2, 4), np.int64), max_new_tokens=5,
                   top_k=10)
    assert out.shape == (2, 9)
    assert np.all((out >= 0) & (out < 66))


@pytest.mark.slow
def test_gpt_trains_on_mesh():
    """16-node FedAvg on a char-level GPT (BASELINE config #4 shape, tiny)."""
    from gym_tpu import Trainer
    from gym_tpu.strategy import FedAvgStrategy, OptimSpec

    data, vocab = build_dataset_small("shakespeare", block_size=32,
                                      start_pc=0.0, end_pc=0.01,
                                      data_root="/tmp/gym_tpu_data")
    ds = ContiguousGPTTrainDataset(data, block_size=32)
    cfg = tiny_cfg(vocab_size=vocab, dropout=0.0)
    res = Trainer(GPT(cfg), ds, ds).fit(
        strategy=FedAvgStrategy(inner_optim=OptimSpec("adamw", lr=3e-3),
                                H=5),
        num_nodes=16, max_steps=25, batch_size=8, minibatch_size=8,
        val_size=8, val_interval=10, show_progress=False,
        log_dir="/tmp/gym_tpu_test_logs",
    )
    first = res.history["train_loss"][0][1]
    last = np.mean([l for _, l in res.history["train_loss"][-5:]])
    assert last < first, (first, last)


# -- data pipeline ---------------------------------------------------------


def test_contiguous_dataset_windows():
    data = np.arange(100, dtype=np.uint16)
    ds = ContiguousGPTTrainDataset(data, block_size=8)
    assert len(ds) == 100 - 8 - 1
    x, y = ds.take(np.array([0, 5]))
    np.testing.assert_array_equal(x[0], np.arange(8))
    np.testing.assert_array_equal(y[0], np.arange(1, 9))
    np.testing.assert_array_equal(x[1], np.arange(5, 13))


def test_noncontiguous_dataset():
    rows = np.arange(40, dtype=np.uint16).reshape(4, 10)
    ds = NonContiguousGPTTrainDataset(rows)
    x, y = ds.take(np.array([1, 3]))
    np.testing.assert_array_equal(x[0], rows[1, :-1])
    np.testing.assert_array_equal(y[1], rows[3, 1:])


def test_lazy_owt_chunks(tmp_path):
    ids, loc, vocab = build_dataset_owt(0.0, 0.004,
                                        data_root=str(tmp_path),
                                        rows_per_chunk=8, row_len=16)
    ds = LazyNonContiguousGPTTrainDataset(ids, loc, max_chunks_in_memory=2)
    assert len(ds) == len(ids) * 8
    x, y = ds.take(np.array([0, 9, 17]))
    assert x.shape == (3, 15) and y.shape == (3, 15)
    np.testing.assert_array_equal(x[0][1:], y[0][:-1])


@pytest.mark.slow
def test_build_dataset_small_cache_roundtrip(tmp_path):
    d1, v1 = build_dataset_small("shakespeare", 32, 0.0, 0.01,
                                 data_root=str(tmp_path))
    d2, v2 = build_dataset_small("shakespeare", 32, 0.0, 0.01,
                                 data_root=str(tmp_path))
    assert v1 == v2 == char_vocab_size() == 66
    np.testing.assert_array_equal(d1, d2)  # cache hit identical
    assert d1.max() < 66


@pytest.mark.slow
def test_get_dataset_selector(tmp_path):
    ds, vocab = get_dataset("shakespeare", 16, 0.0, 0.01,
                            data_root=str(tmp_path))
    assert vocab == 66 and len(ds) > 0
    ds2, vocab2 = get_dataset("owt", 16, 0.0, 0.002,
                              data_root=str(tmp_path))
    assert vocab2 == 50257 and len(ds2) > 0
