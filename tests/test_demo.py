"""Golden-value tests for the DeMo compression stack (SURVEY §4: golden
tests for DCT/top-k vs the reference formulas)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gym_tpu.ops.dct import ChunkedDCT, dct_matrix, largest_divisor_at_most
from gym_tpu.ops.topk_compress import scatter_mean_decode, topk_compress
from gym_tpu.parallel import NodeRuntime
from gym_tpu.strategy import OptimSpec
from gym_tpu.strategy.demo import DeMoStrategy

from test_strategies import make_harness


def test_dct_matrix_is_orthonormal_and_matches_scipy_formula():
    for n in (1, 4, 64):
        d = dct_matrix(n)
        np.testing.assert_allclose(d @ d.T, np.eye(n), atol=1e-5)
    # golden: DCT-II ortho of a known vector (scipy.fft.dct(x, norm='ortho'))
    x = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    # manual: X_k = s_k * sum_n x_n cos(pi (2n+1) k / 8)
    expect = np.array([5.0, -2.2304425, 0.0, -0.15851265], np.float32)
    np.testing.assert_allclose(dct_matrix(4) @ x, expect, atol=1e-5)


def test_divisor_search():
    assert largest_divisor_at_most(1024, 64) == 64
    assert largest_divisor_at_most(96, 64) == 48
    assert largest_divisor_at_most(7, 64) == 7
    assert largest_divisor_at_most(13, 4) == 1
    assert largest_divisor_at_most(50304, 64) == 64


@pytest.mark.parametrize("shape", [(8,), (65,), (16, 24), (3, 3, 4, 8), ()])
def test_chunked_dct_roundtrip(shape):
    codec = ChunkedDCT(shape, target_chunk=4)
    rng = np.random.default_rng(0)
    x = rng.normal(size=shape or ()).astype(np.float32).reshape(codec.shape)
    c = codec.encode(jnp.asarray(x))
    assert c.shape == (codec.n_chunks, codec.chunk_elems)
    y = codec.decode(c)
    np.testing.assert_allclose(np.asarray(y), x, atol=1e-4)


def test_topk_compress_decode():
    c = jnp.asarray(np.array([[0.1, -5.0, 0.2, 3.0],
                              [1.0, 0.0, -2.0, 0.5]], np.float32))
    idx, val = topk_compress(c, 2)
    dense = np.asarray(scatter_mean_decode(idx, val, 4))
    np.testing.assert_allclose(dense, [[0.0, -5.0, 0.0, 3.0],
                                       [1.0, 0.0, -2.0, 0.0]])


def test_scatter_mean_averages_duplicates():
    idx = jnp.asarray(np.array([[1, 1, 3]], np.int32))
    val = jnp.asarray(np.array([[2.0, 4.0, 5.0]], np.float32))
    dense = np.asarray(scatter_mean_decode(idx, val, 4))
    np.testing.assert_allclose(dense, [[0.0, 3.0, 0.0, 5.0]])


def test_packed_topk_matches_full_sort_selection():
    """The packed-key selection (index in low mantissa bits) must pick the
    same magnitude set as a full |value| sort when magnitudes are separated
    beyond the quantization (random normals are)."""
    rng = np.random.default_rng(7)
    c = jnp.asarray(rng.normal(size=(37, 256)).astype(np.float32))
    idx, val = topk_compress(c, 16)
    ref_v, _ = jax.lax.top_k(jnp.abs(c), 16)
    np.testing.assert_allclose(np.sort(np.abs(np.asarray(val)), -1),
                               np.sort(np.asarray(ref_v), -1), rtol=1e-6)
    # returned values are the exact originals at the returned indices
    np.testing.assert_array_equal(
        np.asarray(val),
        np.take_along_axis(np.asarray(c), np.asarray(idx), -1))


def test_packed_topk_ranks_nonfinite_first():
    """An overflowed coefficient must be transmitted, not silently dropped
    (|Inf| OR index would bitcast to a NaN key without the clamp)."""
    c = np.zeros((1, 256), np.float32)
    c[0, 37] = np.inf
    c[0, 101] = -3.0
    idx, val = topk_compress(jnp.asarray(c), 2)
    assert 37 in np.asarray(idx)[0]
    assert np.isinf(np.asarray(val)[0][list(np.asarray(idx)[0]).index(37)])


def test_mean_weights_sum_to_slot_mean():
    from gym_tpu.ops.topk_compress import mean_weights
    idx = jnp.asarray(np.array([[3, 1, 3, 3, 2, 1]], np.int32))
    val = jnp.asarray(np.array([[6.0, 1.0, 3.0, 0.0, 7.0, 5.0]], np.float32))
    w = np.asarray(mean_weights(idx, val))
    # slot 3: mean 3.0 from three picks; slot 1: mean 3.0 from two; slot 2: 7
    np.testing.assert_allclose(w[0, [1, 5]].sum(), 3.0, rtol=1e-6)
    np.testing.assert_allclose(w[0, [0, 2, 3]].sum(), 3.0, rtol=1e-6)
    np.testing.assert_allclose(w[0, 4], 7.0, rtol=1e-6)
    # exact cancellation stays exactly zero (the property sign() relies on)
    w2 = np.asarray(mean_weights(
        jnp.asarray(np.array([[5, 5]], np.int32)),
        jnp.asarray(np.array([[0.3, -0.3]], np.float32))))
    assert (w2 == 0.0).all()


def test_sparse_decode_matches_dense_scatter_decode():
    """gather+matmul sparse decode ≡ scatter-mean grid + dense IDCT, with
    duplicate indices (multi-node concatenation)."""
    from gym_tpu.ops.dct import (decode_chunks, dct_matrix,
                                 sparse_decode_chunks)
    from gym_tpu.ops.topk_compress import mean_weights
    rng = np.random.default_rng(11)
    a, b, G, m = 4, 8, 5, 6
    idx = jnp.asarray(rng.integers(0, a * b, (G, m)).astype(np.int32))
    val = jnp.asarray(rng.normal(size=(G, m)).astype(np.float32))
    d_a, d_b = dct_matrix(a), dct_matrix(b)
    dense = decode_chunks(scatter_mean_decode(idx, val, a * b), d_a, d_b)
    sparse = sparse_decode_chunks(idx, mean_weights(idx, val), d_a, d_b)
    np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                               atol=1e-5)


def test_demo_single_node_sign_sgd():
    """With K=1 and topk == chunk_elems (lossless), the update reduces to
    p ← p − lr·sign(decode(encode(delta))) = p − lr·sign(lr·g) for the
    first step (delta starts at 0) — reference demo.py:142-209."""
    K = 1
    w0 = {"w": np.zeros((K, 8), np.float32)}
    strat = DeMoStrategy(optim_spec=OptimSpec("sgd", lr=0.5),
                         compression_topk=8, compression_chunk=8)
    rt, step_fn, params, state = make_harness(strat, K, w0)
    # no exact-zero grads: sign() of DCT-roundtrip float noise is ±1,
    # same as the reference's float DCT would produce
    g = {"w": np.array([[1.0, -2.0, 3.0, -4.0, 0.5, -0.5, 2.0, 1.5]],
                       np.float32)}
    params, state, m = step_fn(params, state, g, 0)
    out = jax.device_get(params)["w"][0]
    np.testing.assert_allclose(out, -0.5 * np.sign(g["w"][0]), atol=1e-6)
    # residual delta is ~0 when transmission is lossless (delta is stored
    # pre-chunked, pooled per "{a}x{b}" tile signature)
    for d in jax.tree.leaves(jax.device_get(state)["delta"]):
        np.testing.assert_allclose(d, 0.0, atol=1e-5)
    assert float(m["comm_bytes"][0]) == 8 * 8  # 1 chunk × 8 picks × 8 bytes
    # normalized metric contract (strategy.base.comm_metric): f32 scalar
    # per node, like every other strategy
    assert m["comm_bytes"].dtype == np.float32
    assert m["comm_recv_bytes"].dtype == np.float32


def test_demo_multinode_averages_signs():
    """Opposite gradients on two nodes cancel: decoded mean ≈ 0 in the
    transmitted subspace → sign(0)=0 → params unchanged."""
    K = 2
    w0 = {"w": np.zeros((K, 8), np.float32)}
    strat = DeMoStrategy(optim_spec=OptimSpec("sgd", lr=0.5),
                         compression_topk=8, compression_chunk=8)
    rt, step_fn, params, state = make_harness(strat, K, w0)
    gvec = np.array([1.0, -2.0, 3.0, -4.0, 0.5, -0.5, 2.0, 1.0], np.float32)
    g = {"w": np.stack([gvec, -gvec])}
    params, state, m = step_fn(params, state, g, 0)
    out = jax.device_get(params)["w"]
    np.testing.assert_allclose(out, 0.0, atol=1e-6)


def test_demo_residual_accumulates_untransmitted():
    """With topk=1, un-transmitted coefficients stay in delta and carry to
    the next step (decoupled momentum, reference demo.py:170-180)."""
    K = 1
    w0 = {"w": np.zeros((K, 8), np.float32)}
    strat = DeMoStrategy(optim_spec=OptimSpec("sgd", lr=1.0),
                         compression_topk=1, compression_chunk=8)
    rt, step_fn, params, state = make_harness(strat, K, w0)
    g = {"w": np.array([[1.0, -2.0, 3.0, -4.0, 0.5, -0.5, 2.0, 0.0]],
                       np.float32)}
    params, state, m = step_fn(params, state, g, 0)
    d = np.sum([np.abs(leaf).sum()
                for leaf in jax.tree.leaves(jax.device_get(state)["delta"])])
    assert d > 0  # residual nonzero
    assert float(m["comm_bytes"][0]) == 8  # 1 chunk × 1 pick × 8 bytes


def _count_all_gathers(strat, num_nodes, params_np):
    rt = NodeRuntime.create(num_nodes)
    strat.finalize(10)
    init = rt.compile(lambda p: strat.init(p), donate_state=False)
    params = rt.shard_batch(params_np)
    state = init(params)
    grads = rt.shard_batch(jax.tree.map(np.ones_like, params_np))
    tvec = rt.shard_batch(np.zeros(num_nodes, np.int32))
    fn = rt.compile(lambda p, s, g, t: strat.step(g, p, s, t, rt.ctx),
                    donate_state=False)
    hlo = fn.lower(params, state, grads, tvec).compile().as_text()
    # count all-gather OP DEFINITIONS ("... = <ty> all-gather(...)") — a
    # plain substring count also hits fusion operand lists that repeat
    # the producing op's name (older XLA text dumps do this)
    import re
    return len(re.findall(r"=\s+\S+\s+all-gather", hlo))


def test_demo_collective_count_independent_of_depth():
    """The grouped+packed communication phase must emit O(#chunk-shapes)
    all_gathers per step, NOT O(#leaves) (VERDICT r1 #3: the per-leaf loop
    was ~300 collectives/step at GPT-base)."""
    K = 8

    def leaves(n_dense, n_bias):
        p = {f"w{i}": np.zeros((K, 16, 8), np.float32)
             for i in range(n_dense)}
        p.update({f"b{i}": np.zeros((K, 8), np.float32)
                  for i in range(n_bias)})
        return p

    small = _count_all_gathers(
        DeMoStrategy(compression_topk=4, compression_chunk=8), K,
        leaves(2, 2))
    deep = _count_all_gathers(
        DeMoStrategy(compression_topk=4, compression_chunk=8), K,
        leaves(12, 12))
    assert deep == small, (small, deep)  # depth-independent
    # 2 signature groups → 2 gathers (HLO may split start/done pairs)
    assert deep <= 4, deep


def test_demo_recv_accounting():
    """Both byte counters, matching reference demo_impl/demo.py:145-146,
    187-190: receive = (K−1) × transmit for an all-gather exchange."""
    K = 4
    w0 = {"w": np.zeros((K, 8), np.float32)}
    strat = DeMoStrategy(optim_spec=OptimSpec("sgd", lr=0.5),
                         compression_topk=2, compression_chunk=8)
    rt, step_fn, params, state = make_harness(strat, K, w0)
    g = {"w": np.ones((K, 8), np.float32)}
    _, _, m = step_fn(params, state, g, 0)
    tx = float(m["comm_bytes"][0])
    rx = float(m["comm_recv_bytes"][0])
    assert tx == 2 * 8  # 1 chunk × 2 picks × 8 bytes
    assert rx == (K - 1) * tx


def test_demo_grouped_leaves_match_isolated_leaves():
    """Concatenating leaves into one payload must not change any leaf's
    update: a 2-leaf tree gives the same result per leaf as two 1-leaf
    runs."""
    K = 2
    rng = np.random.default_rng(3)
    wa = rng.normal(size=(K, 8)).astype(np.float32)
    wb = rng.normal(size=(K, 16, 8)).astype(np.float32)
    ga = rng.normal(size=(K, 8)).astype(np.float32)
    gb = rng.normal(size=(K, 16, 8)).astype(np.float32)

    def run(params0, grads):
        strat = DeMoStrategy(optim_spec=OptimSpec("sgd", lr=0.1),
                             compression_topk=2, compression_chunk=8)
        rt, step_fn, params, state = make_harness(strat, K, params0)
        p, s, _ = step_fn(params, state, grads, 0)
        return jax.device_get(p)

    both = run({"a": wa, "b": wb}, {"a": ga, "b": gb})
    only_a = run({"a": wa}, {"a": ga})
    only_b = run({"b": wb}, {"b": gb})
    np.testing.assert_allclose(both["a"], only_a["a"], atol=1e-6)
    np.testing.assert_allclose(both["b"], only_b["b"], atol=1e-6)


def test_demo_trains_tiny_net():
    """Convergence smoke on the node mesh, K=4."""
    from gym_tpu import Trainer
    from test_trainer_e2e import TinyLossModel, blobs

    res = Trainer(TinyLossModel(), blobs(512)).fit(
        strategy=DeMoStrategy(optim_spec=OptimSpec("sgd", lr=3e-3),
                              compression_topk=8),
        num_nodes=4, max_steps=30, batch_size=32, minibatch_size=32,
        val_size=0, val_interval=0, show_progress=False,
        log_dir="/tmp/gym_tpu_test_logs",
    )
    first = res.history["train_loss"][0][1]
    last = np.mean([l for _, l in res.history["train_loss"][-5:]])
    assert last < first, (first, last)


@pytest.mark.parametrize("n_nodes", [2, 8])
def test_demo_segmented_pipeline_is_exact(n_nodes):
    """`segment_bytes` bounds the encode/decode transient memory by
    processing tile groups in unrolled, barrier-chained slice segments —
    it must be a pure scheduling choice: forcing many tiny segments (with
    a chunk count
    that does NOT divide evenly, exercising the zero-padding) produces
    bit-identical parameters (sign quantization absorbs float reassociation)
    and delta state equal to float tolerance (XLA contracts the DCT einsums
    in a shape-dependent order). n_nodes=8 also crosses the dense-decode
    route (K·k > 128)."""
    K = n_nodes
    rng = np.random.default_rng(11)
    w0 = {"w": np.repeat(rng.normal(size=(1, 24, 8)).astype(np.float32),
                         K, axis=0),
          "b": np.repeat(rng.normal(size=(1, 8)).astype(np.float32),
                         K, axis=0)}
    grads = {"w": rng.normal(size=(K, 24, 8)).astype(np.float32),
             "b": rng.normal(size=(K, 8)).astype(np.float32)}

    def run(segment_bytes):
        strat = DeMoStrategy(optim_spec=OptimSpec("sgd", lr=0.1),
                             compression_topk=32, compression_chunk=8,
                             segment_bytes=segment_bytes)
        rt, step_fn, params, state = make_harness(strat, K, w0)
        for t in range(3):
            params, state, _ = step_fn(params, state, grads, t)
        return jax.device_get(params), jax.device_get(state)

    p_one, s_one = run(0)            # unsegmented
    p_seg, s_seg = run(2 * 8 * 8 * 4)  # 2 chunks/segment; 7 chunks total
    jax.tree.map(np.testing.assert_array_equal, p_seg, p_one)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        s_seg, s_one)


@pytest.mark.slow
def test_demo_bf16_delta_trains():
    """delta_dtype=bf16 halves the residual-state memory (the knob that
    fits 8-node GPT-2-base DeMo on one chip). The encode still runs in
    f32; the compressed channel (sign-SGD of top-k decode) absorbs the
    storage rounding — training converges like the f32-delta run."""
    from gym_tpu import Trainer
    from test_trainer_e2e import TinyLossModel, blobs

    def run(delta_dtype):
        res = Trainer(TinyLossModel(), blobs(512)).fit(
            strategy=DeMoStrategy(optim_spec=OptimSpec("sgd", lr=3e-3),
                                  compression_topk=8,
                                  delta_dtype=delta_dtype),
            num_nodes=4, max_steps=30, batch_size=32, minibatch_size=32,
            val_size=0, val_interval=0, show_progress=False,
            log_dir="/tmp/gym_tpu_test_logs",
        )
        return [l for _, l in res.history["train_loss"]]

    f32 = run(None)
    bf16 = run(jnp.bfloat16)
    assert np.mean(bf16[-5:]) < bf16[0]
    # same trajectory within the sign-channel's discretization
    np.testing.assert_allclose(np.mean(bf16[-5:]), np.mean(f32[-5:]),
                               rtol=0.1)


def test_demo_vnode_sharded_decode_topology_independent():
    """The vnode-sharded decode (round 4: the gathered picks are node-
    IDENTICAL, so under vnode folding the vmapped program used to decode
    them V times per device; lane j now decodes its chunk-row slice and
    an intra-device all_gather over 'vnode' reassembles) is pure
    reordering: the SAME 8-node config folded onto 8 physical node slots
    (n_virt=1, unsharded decode path) and onto 2 (n_virt=4, sharded
    path) must produce the same loss trajectory. Many picks per chunk
    (K·k > 128) force the dense-scatter decode route the 64-node tracked
    config uses."""
    import jax

    from gym_tpu import Trainer
    from test_trainer_e2e import TinyLossModel, blobs

    def run(n_devices):
        return Trainer(TinyLossModel(), blobs(512)).fit(
            strategy=DeMoStrategy(optim_spec=OptimSpec("sgd", lr=3e-3),
                                  compression_topk=32,
                                  compression_chunk=16),
            num_nodes=8, max_steps=8, batch_size=16, minibatch_size=16,
            val_size=0, val_interval=0, show_progress=False,
            devices=list(range(n_devices)), device="cpu",
            log_dir="/tmp/gym_tpu_test_logs",
        )

    with jax.default_matmul_precision("highest"):
        phys = run(8)    # n_virt=1 — decode replicated per node device
        virt = run(2)    # n_virt=4 — decode sharded over 'vnode'
    a = [l for _, l in phys.history["train_loss"]]
    b = [l for _, l in virt.history["train_loss"]]
    np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-6)
