"""Subprocess worker for the process-restart zero-compile seam
(ISSUE 9, acceptance seam 4).

Builds a tiny serving engine with the device-program registry's
persistent executable tier pointed at ``argv[1]``, warms the COMPLETE
program family, serves one request, and prints the registry counters as
one JSON line.  The parent test runs this twice against the same cache
directory: the first (cold-disk) run must compile, the second
(warm-disk "process restart") must report ``xla_compiles == 0`` — every
build answered by deserializing a persisted executable, zero XLA on the
hot path.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import numpy as np

from gym_tpu import programs
from gym_tpu.models.nanogpt import GPT, GPTConfig
from gym_tpu.serve.engine import InferenceEngine, SamplingParams
from gym_tpu.serve.scheduler import Scheduler

cache_dir = sys.argv[1]
programs.enable_disk_tier(cache_dir)

cfg = GPTConfig(block_size=16, vocab_size=32, n_layer=2, n_head=2,
                n_embd=32, dropout=0.0)
model = GPT(cfg)
params = model.init({"params": jax.random.PRNGKey(0)},
                    np.zeros((1, 4), np.int64), train=False)["params"]

eng = InferenceEngine(params, cfg, num_slots=2, decode_chunk=2)
warm = programs.warm_engine_programs(eng, start=True)
assert warm.wait(timeout=600), "warmup did not finish"

sched = Scheduler(eng, max_queue=4)
h = sched.submit(np.array([1, 2, 3]),
                 SamplingParams(max_new_tokens=4, temperature=0.9,
                                top_k=8, seed=0))
while h.status.value in ("queued", "running"):
    sched.step()
tokens = h.result(timeout=10)
assert len(tokens) == 4

print(json.dumps({
    "counters": programs.default_registry().counters(),
    "xla_compiles": programs.xla_compile_counter(),
    "warm": warm.stats(),
    "tokens": tokens,
}))
