"""The elastic kill drill (ROADMAP: Elastic ZeRO acceptance): the PR-2
kill harness re-armed as a membership-change drill.

A ZeRO run (sharded optimizer state + ZeRO-2 sharded checkpoints) is
``kill -9``-ed at a dispatch boundary past a durable save, then resumed
at a DIFFERENT node count — ``fit(resume="auto", num_nodes=K±1)``. The
drill passes when:

- the resume completes to ``max_steps`` (the reshard path mapped the
  K-node sharded checkpoint onto the K'-node mesh — for K+1 on the
  2-device worker that mesh only exists vnode-folded);
- the pre-kill ``train.csv`` rows are preserved VERBATIM (crash-resume
  logger semantics survive the membership change);
- the stitched loss trajectory stays within tolerance of the
  uninterrupted K-node run. Bit-identity is NOT the bar here — a
  different K partitions the global batch differently by construction —
  so the drill bounds the mean post-resume loss against the baseline's
  tail (measured spread ~0.05; a restart-from-scratch fails by ~0.8).

Subprocess-light like the original harness: one baseline, one crash,
two resumes, all sharing the persistent compile cache.
"""

import json
import os
import shutil
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "_kill_worker.py")
MAX_STEPS = 12
CKPT_INTERVAL = 3
KILL = "dispatch.boundary:kill@8"   # ckpt at step 6 durable, work remains


@pytest.fixture(scope="session")
def el_scratch(tmp_path_factory):
    return tmp_path_factory.mktemp("elastic_drill")


def _run_worker(save_dir, log_dir, *, faults="", result=None, nodes=2,
                timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["GYM_TPU_FAULTS"] = faults
    env["GYM_TPU_IO_RETRIES"] = "2"
    env["GYM_TPU_IO_RETRY_BASE_S"] = "0.01"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, WORKER, "--save-dir", str(save_dir),
           "--log-dir", str(log_dir), "--max-steps", str(MAX_STEPS),
           "--ckpt-interval", str(CKPT_INTERVAL), "--sync-ckpt",
           "--strategy", "zero", "--num-nodes", str(nodes)]
    if result:
        cmd += ["--result", str(result)]
    return subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)


def _train_csv(log_dir):
    with open(os.path.join(str(log_dir), "kill", "train.csv")) as f:
        return f.read()


@pytest.fixture(scope="session")
def el_baseline(el_scratch):
    """Uninterrupted K=2 ZeRO run: the loss oracle, and the seed for the
    shared compile cache."""
    os.environ.setdefault("GYM_TPU_TEST_COMPILE_CACHE",
                          str(el_scratch / "xla_cache"))
    p = _run_worker(el_scratch / "b_ckpt", el_scratch / "b_logs",
                    result=el_scratch / "b.json")
    assert p.returncode == 0, p.stderr[-4000:]
    res = json.loads((el_scratch / "b.json").read_text())
    assert res["steps"] == MAX_STEPS and not res["preempted"]
    return res


@pytest.fixture(scope="session")
def el_crashed(el_scratch, el_baseline):
    """One K=2 run killed -9 at the dispatch boundary; returns the
    checkpoint/log dirs and the pre-kill CSV as written by the corpse."""
    save, log = el_scratch / "c_ckpt", el_scratch / "c_logs"
    p = _run_worker(save, log, faults=KILL)
    assert p.returncode == -signal.SIGKILL, (
        f"expected SIGKILL death, got rc={p.returncode}\n"
        f"{p.stderr[-4000:]}")
    return save, log, _train_csv(log)


@pytest.mark.parametrize("k_new", [1, 3], ids=["K-1", "K+1"])
def test_kill9_resume_at_new_node_count(el_scratch, el_baseline,
                                        el_crashed, k_new):
    save, log, pre_kill_csv = el_crashed
    # each membership resumes from its own copy of the crashed state —
    # the resume writes new (K'-shaped) checkpoints into the tree
    save2 = el_scratch / f"r{k_new}_ckpt"
    log2 = el_scratch / f"r{k_new}_logs"
    if not save2.exists():
        shutil.copytree(save, save2)
        shutil.copytree(log, log2)

    p = _run_worker(save2, log2, result=el_scratch / f"r{k_new}.json",
                    nodes=k_new)
    assert p.returncode == 0, p.stderr[-4000:]
    res = json.loads((el_scratch / f"r{k_new}.json").read_text())
    assert res["steps"] == MAX_STEPS and not res["preempted"]

    # resumed from the durable step-6 checkpoint, not from scratch
    first_logged = res["losses"][0][0]
    assert first_logged == 6, res["losses"]

    # pre-kill rows preserved verbatim, new rows appended after them
    stitched = _train_csv(log2)
    assert stitched.startswith(pre_kill_csv)
    assert len(stitched.splitlines()) == 1 + MAX_STEPS

    # tolerance-bounded trajectory: mean post-resume loss within 0.25 of
    # the uninterrupted run's tail (measured ~0.03-0.05 at K±1; losing
    # the optimizer state or restarting from step 0 overshoots by >0.5)
    tail = [l for s, l in el_baseline["losses"] if s >= first_logged]
    resumed = [l for _, l in res["losses"]]
    assert abs(sum(resumed) / len(resumed)
               - sum(tail) / len(tail)) < 0.25, (resumed, tail)
    assert all(l < 1.0 for l in resumed), resumed
