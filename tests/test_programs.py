"""The unified device-program registry (ISSUE 9).

Pins the registry's three perf layers and the zero-recompile seams:

- **single-flight** — N threads requesting one key run exactly ONE
  build; the rest block on the per-key build lock and share the result.
- **bounded capacity, pinned programs safe** — LRU eviction only ever
  takes UNPINNED entries; an engine's pins are released by weakref when
  the engine dies, never while it could still dispatch.
- **corrupt/stale disk tier degrades, never crashes** — a failed AOT
  compile with the persistent cache enabled is retried once with the
  cache bypassed, surfacing a warning and a fresh executable.
- **one key function** — the jaxpr auditor's serve key set and the
  registry's key set are the same set (the CI gate
  ``registry_key_reconciliation`` asserts in ``python -m
  gym_tpu.analysis``).
- **zero-recompile seams** — trainer→server handoff in-process (the
  supervisor-failover and fleet hot-swap seams live in
  ``test_serve_chaos.py`` / ``test_serve_fleet.py``) and the
  process-restart cold start with a warm disk tier (subprocess:
  ``xla_compiles == 0`` on the second run).
"""

import dataclasses
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gym_tpu.models.nanogpt import GPT, GPTConfig
from gym_tpu.programs import (ProgramDef, ProgramRegistry, WarmupThread,
                              compile_counter, default_registry,
                              program_key, warm_engine_programs)
from gym_tpu.serve.engine import InferenceEngine, SamplingParams
from gym_tpu.serve.scheduler import RequestStatus, Scheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESTART_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "_programs_restart_worker.py")


def _fake_def(name, calls, config=None, fail_first=False):
    """A ProgramDef whose builder is pure host python (no XLA): builds
    are observable via ``calls`` and run in microseconds."""
    def builder():
        calls.append(name)
        time.sleep(0.005)        # widen the race window for the threads
        return lambda *a: (name, len(calls))

    return ProgramDef(name=name, family=name.split("[")[0],
                      config=config or {"n": name}, args=(),
                      donate_args=(), builder=builder)


# -- keys ------------------------------------------------------------------


def test_program_key_deterministic_and_donation_sensitive():
    tpl = jax.ShapeDtypeStruct((4, 8), np.float32)
    canon_a, ha = program_key("p", {"k": 1}, (tpl,), (0,))
    canon_b, hb = program_key("p", {"k": 1}, (tpl,), (0,))
    assert (canon_a, ha) == (canon_b, hb)
    # donation mask, config and avals each change the key — these are
    # exactly the silent-recompile axes the registry keys on
    assert program_key("p", {"k": 1}, (tpl,), ())[1] != ha
    assert program_key("p", {"k": 2}, (tpl,), (0,))[1] != ha
    tpl16 = jax.ShapeDtypeStruct((4, 8), np.float16)
    assert program_key("p", {"k": 1}, (tpl16,), (0,))[1] != ha


# -- single flight ---------------------------------------------------------


def test_n_threads_one_key_exactly_one_build():
    reg = ProgramRegistry()
    calls = []
    pdef = _fake_def("t.sf", calls)
    n = 8
    barrier = threading.Barrier(n)
    results = []

    def worker():
        barrier.wait()
        h = reg.acquire(pdef)
        results.append(h.ensure()())

    threads = [threading.Thread(target=worker) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert calls == ["t.sf"]                  # exactly one build
    assert len(set(results)) == 1             # everyone shares it
    c = reg.counters()
    assert c["builds"] == 1
    assert c["hits"] == n - 1                 # the other N-1 joined


def test_eager_acquire_and_handle_caching():
    reg = ProgramRegistry()
    calls = []
    h = reg.acquire(_fake_def("t.eager", calls), eager=True)
    assert calls == ["t.eager"] and h.built
    h()                                       # hot path: no registry hit
    hits0 = reg.counters()["hits"]
    h()
    assert reg.counters()["hits"] == hits0


# -- eviction / pinning ----------------------------------------------------


class _Owner:
    """weakref-able stand-in for the engine that pins its programs."""


def test_eviction_never_evicts_pinned_in_use():
    reg = ProgramRegistry(capacity=2)
    calls = []
    owner = _Owner()
    ha = reg.acquire(_fake_def("t.a", calls), eager=True,
                     pin_owner=owner)
    reg.acquire(_fake_def("t.b", calls), eager=True)
    reg.acquire(_fake_def("t.c", calls), eager=True)   # over capacity
    names = set(reg.keys().values())
    assert "t.a" in names                     # pinned survived
    assert "t.b" not in names                 # oldest unpinned evicted
    assert reg.counters()["evictions"] == 1
    assert ha()[0] == "t.a"                   # still dispatchable

    # everything pinned: the store runs OVER capacity rather than
    # dropping a live program
    o2, o3 = _Owner(), _Owner()
    reg.acquire(_fake_def("t.c", calls), pin_owner=o2)
    reg.acquire(_fake_def("t.d", calls), eager=True, pin_owner=o3)
    assert len(reg) == 3 and reg.counters()["evictions"] == 1

    # a dead owner releases its pin (weakref finalizer) — the entry
    # becomes evictable again
    del o3
    import gc
    gc.collect()
    reg.acquire(_fake_def("t.e", calls), eager=True)
    assert "t.d" not in set(reg.keys().values())


def test_evicted_unbuilt_handle_raises_keyerror():
    reg = ProgramRegistry(capacity=1)
    calls = []
    h = reg.acquire(_fake_def("t.x", calls))          # registered, unbuilt
    reg.acquire(_fake_def("t.y", calls), eager=True)  # evicts t.x
    with pytest.raises(KeyError, match="evicted"):
        h.ensure()


# -- corrupt / stale disk tier ---------------------------------------------


def test_corrupt_disk_entry_falls_back_with_warning(monkeypatch):
    """A persisted executable that fails to deserialize (corrupt/stale
    cache entry → the AOT compile raises) degrades to ONE retry with
    the persistent cache bypassed — a warning and a fresh compile,
    never a crash."""
    from gym_tpu.programs import registry as regmod
    monkeypatch.setattr(regmod, "_LISTENER_INSTALLED", True)

    calls = {"n": 0}

    class _CorruptLowered:
        def lower(self, *a):
            raise RuntimeError("deserialization failed: corrupt entry")

    def builder():
        calls["n"] += 1
        if calls["n"] == 1:
            return _CorruptLowered()
        return jax.jit(lambda x: x + 1)

    pdef = ProgramDef(
        name="t.corrupt", family="t", config={},
        args=(jax.ShapeDtypeStruct((2,), np.float32),),
        donate_args=(), builder=builder)
    reg = ProgramRegistry()
    with pytest.warns(UserWarning, match="persistent compile cache "
                                         "bypassed"):
        h = reg.acquire(pdef, eager=True)
    assert calls["n"] == 2                    # original + bypass retry
    np.testing.assert_allclose(
        np.asarray(h(jnp.ones((2,), jnp.float32))), 2.0)
    # the bypass retry must re-enable the persistent cache afterwards
    assert jax.config.jax_enable_compilation_cache


def test_corrupt_entry_without_disk_tier_raises(monkeypatch):
    """Without the disk tier there is nothing to bypass: a failing
    build surfaces (a broken builder must not be silently retried)."""
    from gym_tpu.programs import registry as regmod
    monkeypatch.setattr(regmod, "_LISTENER_INSTALLED", False)

    class _Broken:
        def lower(self, *a):
            raise RuntimeError("boom")

    pdef = ProgramDef(name="t.broken", family="t", config={},
                      args=(jax.ShapeDtypeStruct((2,), np.float32),),
                      donate_args=(), builder=lambda: _Broken())
    with pytest.raises(RuntimeError, match="boom"):
        ProgramRegistry().acquire(pdef, eager=True)


# -- track_jit (trainer-path programs) -------------------------------------


def test_track_jit_registers_and_attributes_first_call():
    reg = ProgramRegistry()
    fn = jax.jit(lambda x: x * 2)
    wrapped = reg.track_jit("t.step[x2]", {"lr": 0.1}, (0,), fn,
                            family="t.step")
    out = wrapped(jnp.arange(3.0))
    np.testing.assert_allclose(np.asarray(out), [0.0, 2.0, 4.0])
    c = reg.counters()
    assert c["builds"] == 1 and c["compile_seconds"] > 0
    assert "t.step[x2]" in set(reg.keys().values())
    wrapped(jnp.arange(3.0))                  # steady state: no tracking
    assert reg.counters()["builds"] == 1


# -- warmup ----------------------------------------------------------------


def test_warmup_thread_builds_all_and_single_flights_with_requests():
    reg = ProgramRegistry()
    calls = []
    defs = [_fake_def(f"t.w[{i}]", calls) for i in range(6)]
    t = WarmupThread(defs, registry=reg)
    t.start()
    # a "request" racing the warmup joins the build instead of doubling
    reg.acquire(defs[3]).ensure()
    assert t.wait(timeout=30)
    assert t.stats()["warmed"] == 6 and t.stats()["done"]
    assert sorted(calls) == sorted(f"t.w[{i}]" for i in range(6))
    assert reg.counters()["builds"] == 6      # nothing compiled twice


def test_warmup_survives_builder_failure():
    reg = ProgramRegistry()
    calls = []
    bad = ProgramDef(name="t.bad", family="t", config={}, args=(),
                     donate_args=(),
                     builder=lambda: (_ for _ in ()).throw(
                         RuntimeError("builder exploded")))
    logs = []
    t = WarmupThread([_fake_def("t.ok", calls), bad],
                     registry=reg, log=logs.append)
    t.start()
    assert t.wait(timeout=30)
    assert t.stats()["warmed"] == 1
    assert any("aborted" in line for line in logs)


# -- engine warmup covers the full traffic path ----------------------------


@pytest.fixture(scope="module")
def tiny_serving():
    cfg = GPTConfig(block_size=32, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True)
    model = GPT(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int64), train=False)["params"]
    return cfg, params


def _serve(eng, workload):
    sched = Scheduler(eng, max_queue=len(workload))
    handles = [sched.submit(p, sp) for p, sp in workload]
    for _ in range(5000):
        if all(h.status in (RequestStatus.DONE, RequestStatus.FAILED)
               for h in handles):
            break
        sched.step()
    for h in handles:
        assert len(h.result(timeout=5)) == h.sampling.max_new_tokens
    return handles


def test_warmed_engine_serves_with_zero_builds(tiny_serving):
    """After background warmup finishes, NO request — any prompt
    length, any sampling — triggers a build: the ≤⌈log2(block)⌉+1
    compile bound is paid entirely off the request path (the cold-p99
    TTFT mechanism, pinned here structurally; measured in
    ``bench.py --coldstart-only``)."""
    cfg, params = tiny_serving
    eng = InferenceEngine(params, cfg, num_slots=2, decode_chunk=2)
    warm = warm_engine_programs(eng, start=True)
    assert warm.wait(timeout=600)
    st = warm.stats()
    bound = (cfg.block_size - 1).bit_length() + 1
    # prefill buckets + decode + admit + the chunk-1 decode twin
    assert st["warmed"] == st["total"] == bound + 3
    builds0 = compile_counter()
    rng = np.random.default_rng(0)
    workload = [
        (rng.integers(0, cfg.vocab_size, n),
         SamplingParams(max_new_tokens=3, temperature=0.9, top_k=8,
                        seed=n))
        for n in (1, 2, 5, 9, 17, 29)]                # every bucket
    # (29 + 3 new tokens fills block_size exactly; 29 still buckets
    # to the top power-of-two prefill program)
    _serve(eng, workload)
    assert compile_counter() == builds0
    assert eng.stats.prefill_compiles == 0


# -- seam 1: trainer→server handoff (in-process) ---------------------------


@pytest.mark.slow
def test_trainer_to_server_handoff_zero_recompile(tmp_path):
    """One process, one registry: a tiny ``fit`` registers its step
    programs next to the serving programs; the server stack built from
    the trained params serves, and REBUILDING it (the restore/handoff
    path) triggers zero new builds — the warm handoff ROADMAP item 3
    promises, pinned on the shared counter."""
    from gym_tpu import Trainer
    from gym_tpu.data import ArrayDataset
    from gym_tpu.strategy import OptimSpec, SimpleReduceStrategy

    cfg = GPTConfig(block_size=32, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 48, (32, 33))
    ds = ArrayDataset(toks[:, :-1].astype(np.int64),
                      toks[:, 1:].astype(np.int64))
    res = Trainer(GPT(cfg), ds).fit(
        strategy=SimpleReduceStrategy(OptimSpec("adamw", lr=1e-3)),
        num_nodes=1, max_steps=2, batch_size=4, val_size=0,
        val_interval=0, show_progress=False, seed=1)
    names = set(default_registry().keys().values())
    assert any(n.startswith("trainer.step[") for n in names)

    workload = [(np.arange(1, 6), SamplingParams(max_new_tokens=4,
                                                 seed=7))]
    eng = InferenceEngine(res.params, cfg, num_slots=2)
    first = _serve(eng, workload)[0].result(timeout=5)
    builds0 = compile_counter()
    # the handoff/restore rebuild: same config, fresh engine
    eng2 = InferenceEngine(res.params, cfg, num_slots=2)
    second = _serve(eng2, workload)[0].result(timeout=5)
    assert compile_counter() == builds0       # zero-recompile handoff
    assert second == first                    # same params, same stream
    names = set(default_registry().keys().values())
    assert any(n.startswith("serve.prefill[") for n in names)


# -- seam 4: process restart with a warm disk tier -------------------------


@pytest.mark.slow
def test_process_restart_zero_xla_compiles(tmp_path):
    """The restart drill's pin, at the python level: two processes, same
    config, same program-cache dir. The first compiles and persists;
    the second — a server restart — reports ``xla_compiles == 0``:
    every program deserialized, zero XLA on the hot path."""
    cache_dir = str(tmp_path / "progcache")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)               # plain 1-device subprocess
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def run():
        p = subprocess.run([sys.executable, RESTART_WORKER, cache_dir],
                           env=env, cwd=REPO, capture_output=True,
                           text=True, timeout=600)
        assert p.returncode == 0, p.stderr[-2000:]
        return json.loads(p.stdout.strip().splitlines()[-1])

    cold = run()
    assert cold["xla_compiles"] == cold["counters"]["builds"] > 0
    assert cold["counters"]["disk_hits"] == 0
    warm = run()
    assert warm["xla_compiles"] == 0          # the acceptance pin
    assert warm["counters"]["disk_hits"] == warm["counters"]["builds"] \
        == cold["counters"]["builds"]
    assert warm["tokens"] == cold["tokens"]   # same executables, bitwise
    # the deserializing restart is also measurably cheaper
    assert (warm["counters"]["compile_seconds"]
            < cold["counters"]["compile_seconds"])


# -- satellite: generate_fast cache collision audit ------------------------


def test_generate_fast_cache_distinguishes_configs():
    """Two configs with IDENTICAL param trees and arg shapes (only
    ``n_head`` differs — the pure-static knob) must occupy two distinct
    ``_cached_decode_program`` entries: the maxsize=32 cache keys on
    the full config astuple, so a cross-config collision — the one
    failure its lru key could silently produce — is impossible."""
    from gym_tpu.models.nanogpt import _cached_decode_program, \
        generate_fast

    cfg_a = GPTConfig(block_size=16, vocab_size=32, n_layer=1, n_head=2,
                      n_embd=16, dropout=0.0)
    cfg_b = dataclasses.replace(cfg_a, n_head=4)   # same param shapes
    model = GPT(cfg_a)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 4), np.int64),
                        train=False)["params"]
    prompt = np.arange(1, 5)[None]
    misses0 = _cached_decode_program.cache_info().misses
    out_a = generate_fast(params, cfg_a, prompt, 3, seed=0)
    out_b = generate_fast(params, cfg_b, prompt, 3, seed=0)
    assert _cached_decode_program.cache_info().misses == misses0 + 2
    assert out_a.shape == out_b.shape == (1, 7)
    # and a same-config repeat is a hit, not a third entry
    generate_fast(params, cfg_a, prompt, 3, seed=0)
    assert _cached_decode_program.cache_info().misses == misses0 + 2
