"""Network-simulation subsystem (ISSUE 3): closed-form oracles for the
alpha-beta cost model, topology semantics, per-strategy collective
traces, and trace-vs-logged reconciliation on a real fit.

Everything except the reconciliation fit is pure host math — no device.
"""

import csv
import json

import jax
import numpy as np
import pytest

from gym_tpu.sim import (CollectiveEvent, Link, NetworkSimulator, Topology,
                         collective_time, events_tx_bytes, loss_frontier,
                         resolve_topology, ring_all_gather_time,
                         ring_all_reduce_time, tree_all_reduce_time,
                         tree_broadcast_time)
from gym_tpu.strategy import (DeMoStrategy, DiLoCoStrategy, FedAvgStrategy,
                              OptimSpec, SimpleReduceStrategy,
                              SPARTADiLoCoStrategy, SPARTAStrategy,
                              ZeroReduceStrategy)

PARAMS = {"w": jax.ShapeDtypeStruct((100, 64), np.float32),
          "b": jax.ShapeDtypeStruct((64,), np.float32)}
PBYTES = (100 * 64 + 64) * 4


# -- cost-model oracles ----------------------------------------------------


def test_ring_all_reduce_closed_form_exact():
    """The ISSUE 3 oracle: ring all-reduce of N bytes over k homogeneous
    links must equal 2(k−1)/k · N/bw + 2(k−1)·alpha EXACTLY."""
    N, bw, alpha = 1.6e6, 1.25e8, 5e-3
    for k in (2, 3, 4, 8, 16):
        links = [Link(bw, alpha)] * k
        expect = 2 * (k - 1) / k * N / bw + 2 * (k - 1) * alpha
        assert ring_all_reduce_time(N, links) == expect, k


def test_ring_all_reduce_bottleneck_link_dominates():
    """Heterogeneous ring: every round waits for its slowest hop, so one
    slow link sets the pace for the whole ring."""
    N, k = 8e6, 4
    fast, slow = Link(1e9, 1e-4), Link(1e8, 5e-2)
    t_mixed = ring_all_reduce_time(N, [fast, fast, fast, slow])
    t_slow = ring_all_reduce_time(N, [slow] * k)
    assert t_mixed == t_slow


def test_ring_all_gather_and_reduce_scatter():
    N, bw, alpha, k = 4e6, 1e9, 1e-3, 8
    links = [Link(bw, alpha)] * k
    expect = (k - 1) / k * N / bw + (k - 1) * alpha
    assert ring_all_gather_time(N, links) == expect
    # reduce-scatter is the mirror image: same rounds, same chunk
    ev_rs = CollectiveEvent("reduce_scatter", N, k)
    ev_ag = CollectiveEvent("all_gather", N, k)
    topo = Topology("t", k, intra=Link(bw, alpha), inter=Link(bw, alpha))
    assert collective_time(ev_rs, topo) == collective_time(ev_ag, topo)


def test_tree_vs_ring_latency_bandwidth_trade():
    """Tree all-reduce pays log(k) latency terms vs the ring's linear k,
    but full-payload hops vs the ring's 1/k chunks: tiny messages favor
    the tree, big ones the ring."""
    k = 16
    links = [Link(1e8, 10e-3)] * k
    bneck = Link(1e8, 10e-3)
    tiny, huge = 1e3, 1e9
    assert tree_all_reduce_time(tiny, bneck, k) \
        < ring_all_reduce_time(tiny, links)
    assert ring_all_reduce_time(huge, links) \
        < tree_all_reduce_time(huge, bneck, k)
    # broadcast = half an all-reduce on the same tree
    assert tree_broadcast_time(tiny, bneck, k) * 2 \
        == tree_all_reduce_time(tiny, bneck, k)


def test_hierarchical_reduces_to_flat_when_intra_equals_inter():
    """The ISSUE 3 topology oracle: a hierarchical topology with
    intra == inter must price every collective identically to the flat
    network (nodes_per_host=1) of the same link."""
    link = Link(2.5e8, 2e-3)
    k = 8
    hier = Topology("h", k, intra=link, inter=link, nodes_per_host=4)
    flat = Topology("f", k, intra=link, inter=link, nodes_per_host=1)
    for op, nbytes in (("all_reduce", 1e6), ("all_gather", 3e5),
                       ("reduce_scatter", 3e5), ("broadcast", 1e4),
                       ("p2p", 1e4)):
        ev = CollectiveEvent(op, nbytes, k)
        assert collective_time(ev, hier) == collective_time(ev, flat), op


def test_hierarchical_inter_host_hop_bottlenecks_the_ring():
    k = 8
    intra, inter = Link(4e10, 1e-6), Link(1.25e8, 5e-2)
    hier = Topology("h", k, intra=intra, inter=inter, nodes_per_host=4)
    ev = CollectiveEvent("all_reduce", 1e6, k)
    # rounds wait on the inter-host hop → identical to an all-inter ring
    flat_inter = Topology("f", k, intra=inter, inter=inter)
    assert collective_time(ev, hier) == collective_time(ev, flat_inter)
    # but a group that fits inside one host runs at intra speed
    ev4 = CollectiveEvent("all_reduce", 1e6, 4)
    flat_intra = Topology("f", 4, intra=intra, inter=intra)
    assert collective_time(ev4, hier) == collective_time(ev4, flat_intra)


def test_presets_resolve_and_order():
    wan = resolve_topology("wan", 4)
    dc = resolve_topology("datacenter", 4)
    fed = resolve_topology("federated", 4)
    assert resolve_topology("cross-region", 4) == wan
    ev = CollectiveEvent("all_reduce", 1e8, 4)
    # consumer uplinks < WAN < datacenter, by construction
    assert collective_time(ev, dc) < collective_time(ev, wan) \
        < collective_time(ev, fed)
    with pytest.raises(ValueError, match="unknown topology preset"):
        resolve_topology("petabit-hyperloop", 4)
    with pytest.raises(ValueError, match="has 2 nodes"):
        resolve_topology(Topology("t", 2, intra=Link(1, 0),
                                  inter=Link(1, 0)), 8)


def test_collective_event_validation_and_tx():
    with pytest.raises(ValueError, match="unknown collective op"):
        CollectiveEvent("all_to_all", 10.0, 4)
    assert CollectiveEvent("all_reduce", 100.0, 4).per_node_tx() == 150.0
    assert CollectiveEvent("all_gather", 100.0, 4).per_node_tx() == 75.0
    assert CollectiveEvent("broadcast", 100.0, 4).per_node_tx() == 100.0
    assert CollectiveEvent("all_reduce", 100.0, 4,
                           tx_bytes=7.0).per_node_tx() == 7.0
    # group=1 collectives are free and silent
    topo = resolve_topology("wan", 2)
    assert collective_time(CollectiveEvent("all_reduce", 1e6, 1), topo) == 0


# -- per-strategy traces ---------------------------------------------------


def test_simple_reduce_trace_every_step():
    s = SimpleReduceStrategy()
    for t in (0, 1, 17):
        evs = s.comm_events(t, PARAMS, 4)
        assert [e.op for e in evs] == ["all_reduce"]
        assert evs[0].bytes == PBYTES
        assert events_tx_bytes(evs) == 2 * 3 / 4 * PBYTES


def test_diloco_trace_cadence_and_bytes():
    s = DiLoCoStrategy(H=10)
    assert s.comm_events(0, PARAMS, 4) == []     # step>0 gate
    assert s.comm_events(7, PARAMS, 4) == []
    evs = s.comm_events(20, PARAMS, 4)
    assert [e.op for e in evs] == ["all_reduce"]
    assert events_tx_bytes(evs) == 2 * 3 / 4 * PBYTES
    assert s.comm_events(5, PARAMS, 1) == []     # K=1: nothing to sync
    # shard_outer pays the extra master all_gather: 3(K−1)/K·|θ|
    sh = DiLoCoStrategy(H=10, shard_outer=True)
    evs = sh.comm_events(10, PARAMS, 4)
    assert [e.op for e in evs] == ["all_reduce", "all_gather"]
    assert events_tx_bytes(evs) == 3 * 3 / 4 * PBYTES


def test_fedavg_trace_gate_and_islands():
    s = FedAvgStrategy(H=5)
    assert s.comm_events(4, PARAMS, 4) == []
    assert s.comm_events(0, PARAMS, 4) == []
    assert events_tx_bytes(s.comm_events(5, PARAMS, 4)) \
        == 2 * 3 / 4 * PBYTES
    isl = FedAvgStrategy(H=5, island_size=2)
    evs = isl.comm_events(5, PARAMS, 4)
    assert [e.op for e in evs] == ["all_gather"]
    assert evs[0].group == 2 and evs[0].bytes == 2 * PBYTES
    # island accounting: one full-model transmit per node (:61-69)
    assert events_tx_bytes(evs) == PBYTES


def test_sparta_trace_counts_realized_mask_bytes():
    """The host trace replays the shared-PRNG masks, so its byte count is
    the REALIZED draw — it must match the jitted step's metric exactly,
    not just in expectation."""
    from gym_tpu.parallel import NodeRuntime
    K, n = 4, 1000
    s = SPARTAStrategy(inner_optim=OptimSpec("sgd", lr=0.0), p_sparta=0.3)
    s.finalize(10)
    rt = NodeRuntime.create(K, None)
    s.bind_ctx(rt.ctx)
    params = rt.shard_batch(
        {"w": np.zeros((K, n), np.float32)})
    state = rt.compile(lambda p: s.init(p), donate_state=False)(params)
    raw = rt.compile(lambda p, st, g, t: s.step(g, p, st, t, rt.ctx),
                     donate_state=False)
    template = {"w": jax.ShapeDtypeStruct((n,), np.float32)}
    for t in (0, 3):
        tvec = rt.shard_batch(np.full(K, t, np.int32))
        _, _, m = raw(params, state, params, tvec)
        metric = float(np.asarray(m["comm_bytes"])[0])
        trace = events_tx_bytes(s.comm_events(t, template, K))
        assert trace == pytest.approx(metric, rel=1e-6), t


def test_zero_reduce_trace_follows_schedule():
    s = ZeroReduceStrategy()
    # unbound ctx → conservative fallback accounting
    assert events_tx_bytes(s.comm_events(0, PARAMS, 4)) \
        == pytest.approx((2 * 3 / 4 + 3 / 4) * PBYTES)

    class _Ctx:
        axes = ("node",)
        num_nodes = 4
        pp_axes = ()
    s.bind_ctx(_Ctx())
    evs = s.comm_events(0, PARAMS, 4)
    assert [e.op for e in evs] == ["reduce_scatter", "all_gather"]
    assert events_tx_bytes(evs) == pytest.approx(2 * 3 / 4 * PBYTES)


def test_demo_trace_matches_payload_accounting():
    s = DeMoStrategy(compression_topk=8, compression_chunk=16)
    evs = s.comm_events(0, PARAMS, 4)
    assert all(e.op == "all_gather" for e in evs)
    # payload-once accounting, K-independent (reference data_transmit)
    assert events_tx_bytes(evs) == events_tx_bytes(s.comm_events(0, PARAMS, 1))
    # n_chunks per leaf from the same codec the strategy step uses;
    # 8 picks × 8 bytes (f32 val + bitcast i32 idx) per chunk
    from gym_tpu.ops.dct import codec_for
    n_chunks = sum(codec_for(tuple(p.shape), 16).n_chunks
                   for p in (PARAMS["w"], PARAMS["b"]))
    assert events_tx_bytes(evs) == n_chunks * 8 * 8


def test_sparta_diloco_trace_composes_both_modules():
    s = SPARTADiLoCoStrategy(p_sparta=0.5, H=4)
    assert {e.label for e in s.comm_events(4, PARAMS, 4)} \
        >= {"sparse_avg", "outer_avg"}
    assert [e.label for e in s.comm_events(3, PARAMS, 4)] == ["sparse_avg"]


def test_diloco_participation_trace_prices_alive_group():
    s = DiLoCoStrategy(H=5, participation=0.6)
    from gym_tpu.strategy import alive_mask
    comm = s.communication_modules[0]
    alive = np.asarray(alive_mask(comm.fault_seed, 5, 8, 0.6))
    evs = s.comm_events(5, PARAMS, 8)
    assert evs[0].group == int(alive.sum())
    g = int(alive.sum())
    expect = float(alive.mean()) * 2 * (g - 1) / g * PBYTES
    assert events_tx_bytes(evs) == pytest.approx(expect)


def test_noloco_trace_gossip_cadence_pairs_and_pricing():
    """NoLoCo's trace: p2p gossip rounds at the H cadence, per-node tx =
    |θ| regardless of K, pairs a fixed-point-free permutation matching
    the host twin — and the cost model prices the round as ONE
    concurrent exchange on every preset, not a serial K-hop chain."""
    from gym_tpu.strategy import NoLoCoStrategy

    s = NoLoCoStrategy(H=5)
    assert s.comm_events(0, PARAMS, 4) == []     # step>0 gate
    assert s.comm_events(3, PARAMS, 4) == []
    assert s.comm_events(5, PARAMS, 1) == []     # K=1: no partner
    for K in (2, 4, 8):
        evs = s.comm_events(5, PARAMS, K)
        assert [e.op for e in evs] == ["p2p"]
        assert evs[0].bytes == PBYTES
        # ONE |θ| per node per round — the whole point vs all-reduce's
        # 2(K−1)/K·|θ|
        assert events_tx_bytes(evs) == PBYTES
        # pairs are (sender, receiver) of the actual dataflow: node i
        # reads from σ(i), so the edge is (σ(i), i)
        src_of = {recv: send for send, recv in evs[0].pairs}
        assert sorted(src_of) == sorted(src_of.values()) == list(range(K))
        assert all(i != j for i, j in evs[0].pairs)   # derangement
        np.testing.assert_array_equal(
            np.asarray([src_of[i] for i in range(K)]),
            s.partner_permutation(5, K))
    # the draw changes every gossip step (fresh mixing matrix)
    assert s.comm_events(5, PARAMS, 8)[0].pairs \
        != s.comm_events(10, PARAMS, 8)[0].pairs
    # pricing: every preset prices the round; a gossip round is one
    # concurrent p2p hop, so it must cost (far) less than the same
    # bytes through a K-node ring all-reduce on the same preset
    for preset in ("wan", "datacenter", "federated"):
        topo = resolve_topology(preset, 8)
        ev = s.comm_events(5, PARAMS, 8)[0]
        t_gossip = collective_time(ev, topo)
        t_ar = collective_time(CollectiveEvent("all_reduce", PBYTES, 8),
                               topo)
        assert 0 < t_gossip < t_ar, preset


def test_gossip_round_time_prices_the_links_pairs_cross():
    """Hierarchical topology: an all-intra-host pairing costs the fast
    link's single hop; one cross-host pair drags the round to the slow
    link — the per-edge pricing the `pairs` field exists for."""
    from gym_tpu.sim.cost_model import gossip_round_time, p2p_time

    intra, inter = Link(4e10, 1e-6), Link(1.25e8, 5e-2)
    hier = Topology("h", 8, intra=intra, inter=inter, nodes_per_host=4)
    nbytes = 1e6
    # nodes 0-3 on host 0, 4-7 on host 1: pair within hosts
    intra_pairs = ((0, 1), (1, 0), (2, 3), (3, 2),
                   (4, 5), (5, 4), (6, 7), (7, 6))
    cross_pairs = ((0, 4), (4, 0), (1, 5), (5, 1),
                   (2, 6), (6, 2), (3, 7), (7, 3))
    assert gossip_round_time(nbytes, intra_pairs, hier) \
        == p2p_time(nbytes, intra)
    assert gossip_round_time(nbytes, cross_pairs, hier) \
        == p2p_time(nbytes, inter)
    # self-pairs (a node sitting out) are free
    assert gossip_round_time(nbytes, ((0, 0), (1, 1)), hier) == 0.0
    # the CollectiveEvent path dispatches on pairs
    ev = CollectiveEvent("p2p", nbytes, 8, pairs=intra_pairs)
    assert collective_time(ev, hier) == p2p_time(nbytes, intra)


def test_dynamiq_trace_prices_compressed_wire_bytes():
    """DynamiQ's trace declares the codec's honest wire bytes (data +
    per-tile scales / top-k indices) on the canonical reduce-scatter +
    all-gather schedule — ~bits/32 of the dense cost, priced on every
    preset."""
    from gym_tpu.strategy import DynamiQStrategy, SimpleReduceStrategy

    K = 4
    dense_tx = events_tx_bytes(
        SimpleReduceStrategy().comm_events(0, PARAMS, K))
    for codec, lo, hi in (("int8", 0.25, 0.30), ("int4", 0.125, 0.18)):
        s = DynamiQStrategy(codec=codec)
        evs = s.comm_events(0, PARAMS, K)
        assert [e.op for e in evs] == ["reduce_scatter", "all_gather"]
        ratio = events_tx_bytes(evs) / dense_tx
        assert lo <= ratio <= hi, (codec, ratio)
        assert s.comm_events(0, PARAMS, 1) == []   # K=1: silent
    # every preset prices the compressed schedule below the dense one
    s8 = DynamiQStrategy(codec="int8")
    for preset in ("wan", "datacenter", "federated"):
        topo = resolve_topology(preset, K)
        t_c = sum(collective_time(e, topo)
                  for e in s8.comm_events(0, PARAMS, K))
        t_d = sum(collective_time(e, topo)
                  for e in SimpleReduceStrategy().comm_events(0, PARAMS, K))
        assert 0 < t_c < t_d, preset
    # top-k: 5% of elements at 8 B each, per hop convention
    st = DynamiQStrategy(codec="topk", frac=0.05)
    evs = st.comm_events(0, PARAMS, K)
    n = 100 * 64 + 64
    assert evs[0].bytes == st.codec.wire_bytes(n)
    assert evs[1].bytes == K * st.codec.wire_bytes(-(-n // K))


def test_compressed_outer_loop_traces_price_codec_wire_bytes():
    """ISSUE 12: the whole CompressedLink family declares its codec's
    honest wire bytes on the H cadence — DiLoCo/demo_outer as a
    compressed all_reduce, NoLoCo as a compressed p2p gossip round with
    the same pairs as the dense cell — and every preset prices the
    compressed round strictly below the dense one."""
    from gym_tpu.strategy import (DecoupledMomentumStrategy,
                                  DiLoCoStrategy, NoLoCoStrategy)

    K, H = 4, 5
    n = 100 * 64 + 64
    cases = [
        (DiLoCoStrategy(H=H, codec="int4"), DiLoCoStrategy(H=H),
         "all_reduce"),
        (NoLoCoStrategy(H=H, codec="int4"), NoLoCoStrategy(H=H), "p2p"),
        (DecoupledMomentumStrategy(H=H, codec="topk", frac=0.05),
         DecoupledMomentumStrategy(H=H, codec=None), "all_reduce"),
    ]
    for comp, dense, op in cases:
        name = type(comp).__name__
        assert comp.comm_events(0, PARAMS, K) == []      # step>0 gate
        assert comp.comm_events(H - 1, PARAMS, K) == []
        assert comp.comm_events(H, PARAMS, 1) == []      # K=1: silent
        evs = comp.comm_events(H, PARAMS, K)
        evs_d = dense.comm_events(H, PARAMS, K)
        assert [e.op for e in evs] == [op], name
        # declared wire bytes = the link's accounting, well below dense
        link = comp.communication_modules[0].link
        assert evs[0].bytes == link.wire_bytes(n)
        assert evs[0].bytes < 0.5 * evs_d[0].bytes, name
        # the dense emulation bound covers the moved f32 payload (the
        # gather-emulated gossip moves the K·|θ| assembled output)
        assert evs[0].emulated_bytes >= 4.0 * n
        # gossip pairs identical to the dense cell's (codec is
        # orthogonal to the partner draw)
        if op == "p2p":
            assert evs[0].pairs == evs_d[0].pairs
        # per-preset pricing: compressed < dense
        for preset in ("wan", "datacenter", "federated"):
            topo = resolve_topology(preset, K)
            t_c = sum(collective_time(e, topo) for e in evs)
            t_d = sum(collective_time(e, topo) for e in evs_d)
            assert 0 < t_c < t_d, (name, preset)


def test_dynamiq_metric_matches_trace_exactly_under_stochastic_rounding():
    """Sparta-style realized accounting: stochastic rounding randomizes
    the VALUES on the wire, never the byte count — the jitted step's
    comm_bytes metric and the host trace must agree exactly at every
    step, not in expectation."""
    from gym_tpu.parallel import NodeRuntime
    from gym_tpu.strategy import DynamiQStrategy

    K, n = 4, 1000
    s = DynamiQStrategy(optim_spec=OptimSpec("sgd", lr=0.01), codec="int8")
    s.finalize(10)
    rt = NodeRuntime.create(K, None)
    s.bind_ctx(rt.ctx)
    params = rt.shard_batch({"w": np.ones((K, n), np.float32)})
    state = rt.compile(lambda p: s.init(p), donate_state=False)(params)
    raw = rt.compile(lambda p, st, g, t: s.step(g, p, st, t, rt.ctx),
                     donate_state=False)
    template = {"w": jax.ShapeDtypeStruct((n,), np.float32)}
    for t in (0, 3):
        tvec = rt.shard_batch(np.full(K, t, np.int32))
        _, _, m = raw(params, state, params, tvec)
        metric = float(np.asarray(m["comm_bytes"])[0])
        trace = events_tx_bytes(s.comm_events(t, template, K))
        assert trace == pytest.approx(metric, rel=1e-6), t


# -- simulator -------------------------------------------------------------


def test_simulator_overlap_toggle_and_frontier():
    sim = NetworkSimulator(SimpleReduceStrategy(), PARAMS, 4, "wan")
    sim_ov = NetworkSimulator(SimpleReduceStrategy(), PARAMS, 4, "wan",
                              overlap=True)
    comm = sim.comm_time(0)
    assert comm > 0
    r = sim.simulate(10, compute_s_per_step=0.05)
    r_ov = sim_ov.simulate(10, compute_s_per_step=0.05)
    assert r.total_s == pytest.approx(10 * (0.05 + comm))
    assert r_ov.total_s == pytest.approx(10 * max(0.05, comm))
    assert r_ov.total_s < r.total_s
    fr = loss_frontier(r, [(0, 3.0), (9, 2.0)])
    assert fr[0] == (pytest.approx(0.05 + comm), 3.0)
    assert fr[-1][1] == 2.0 and fr[-1][0] == pytest.approx(r.total_s)


def test_simulator_diloco_beats_allreduce_on_wan_not_datacenter():
    """The motivating trade-off: on WAN links DiLoCo's H-fold comm saving
    dominates; inside a datacenter the network is fast enough that the
    two are nearly tied (compute-bound)."""
    compute = 0.02
    def total(strategy, preset):
        return NetworkSimulator(strategy, PARAMS, 8, preset).simulate(
            50, compute).total_s
    wan_d = total(DiLoCoStrategy(H=10), "wan")
    wan_a = total(SimpleReduceStrategy(), "wan")
    assert wan_d < wan_a / 2
    dc_d = total(DiLoCoStrategy(H=10), "datacenter")
    dc_a = total(SimpleReduceStrategy(), "datacenter")
    assert dc_a / dc_d < 1.2  # near-tied: compute dominates


# -- reconciliation against a real fit (the ISSUE 3 acceptance oracle) -----


def _noloco():
    from gym_tpu.strategy import NoLoCoStrategy
    return NoLoCoStrategy(optim_spec=OptimSpec("adamw", lr=1e-3), H=7)


def _dynamiq():
    from gym_tpu.strategy import DynamiQStrategy
    return DynamiQStrategy(optim_spec=OptimSpec("adamw", lr=1e-3),
                           codec="int8")


def _dynamiq_topk():
    from gym_tpu.strategy import DynamiQStrategy
    return DynamiQStrategy(optim_spec=OptimSpec("adamw", lr=1e-3),
                           codec="topk", frac=0.05)


def _diloco_int4():
    return DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=1e-3), H=7,
                          codec="int4")


def _noloco_int4():
    from gym_tpu.strategy import NoLoCoStrategy
    return NoLoCoStrategy(optim_spec=OptimSpec("adamw", lr=1e-3), H=7,
                          codec="int4")


def _demo_outer():
    from gym_tpu.strategy import DecoupledMomentumStrategy
    return DecoupledMomentumStrategy(optim_spec=OptimSpec("adamw", lr=1e-3),
                                     H=7, frac=0.05)


@pytest.mark.parametrize("strategy_fn", [
    lambda: SimpleReduceStrategy(optim_spec=OptimSpec("adamw", lr=1e-3)),
    lambda: DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=1e-3), H=7),
    _noloco, _dynamiq, _dynamiq_topk,
    _diloco_int4, _noloco_int4, _demo_outer,
], ids=["simple_reduce", "diloco", "noloco", "dynamiq_int8",
        "dynamiq_topk", "diloco_int4", "noloco_int4", "demo_outer"])
def test_trace_reconciles_with_cum_comm_bytes_30_step_fit(
        strategy_fn, tmp_path):
    """Trace totals vs the logged cum_comm_bytes column on a REAL 30-step
    fit: equal to within float32 rounding, and the sim_step_s CSV column
    + summary sim_* keys exist and are sane."""
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from gym_tpu import Trainer
    from gym_tpu.data import ArrayDataset

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, batch, train=True):
            x, y = batch
            x = x.reshape((x.shape[0], -1))
            h = nn.relu(nn.Dense(32)(x))
            return optax.softmax_cross_entropy_with_integer_labels(
                nn.Dense(10)(h).astype(jnp.float32), y).mean()

    rng = np.random.default_rng(0)
    ds = ArrayDataset(rng.normal(size=(2048, 8, 8)).astype(np.float32),
                      rng.integers(0, 10, 2048).astype(np.int32))
    res = Trainer(MLP(), ds).fit(
        strategy=strategy_fn(), num_nodes=4, max_steps=30, batch_size=8,
        minibatch_size=8, val_size=0, val_interval=0, show_progress=False,
        network="wan", log_dir=str(tmp_path), run_name="rec")
    with open(tmp_path / "rec" / "summary.json") as f:
        summary = json.load(f)
    cum = summary["cum_comm_bytes"]
    trace = summary["trace_tx_bytes"]
    assert cum > 0
    assert trace == pytest.approx(cum, rel=1e-5)
    assert res.sim["trace_tx_bytes"] == trace
    assert summary["sim_total_s"] >= summary["sim_comm_s"] > 0
    # per-row sim column: present for every one of the 30 steps
    with open(tmp_path / "rec" / "train.csv") as f:
        rows = list(csv.reader(f))
    assert rows[0][-1] == "sim_step_s"
    assert len(rows) == 31
    assert all(float(r[-1]) >= 0 for r in rows[1:])
    assert len(res.history["sim_step_s"]) == 30


def test_int4_diloco_fit_tracks_dense_and_ablation_diverges(tmp_path):
    """The ISSUE 12 error-feedback acceptance, fit-level: on the
    standard gym workload, int4 DiLoCo's loss trajectory lands within
    tolerance of dense DiLoCo — the compressed outer deltas (with the
    default error-feedback residual) cost essentially nothing — while
    ablating the residual on an aggressive top-k link demonstrably
    diverges from the EF run (the dropped outer mass never reaches the
    masters, so the replicas stop converging together)."""
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from gym_tpu import Trainer
    from gym_tpu.data import ArrayDataset

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, batch, train=True):
            x, y = batch
            x = x.reshape((x.shape[0], -1))
            h = nn.relu(nn.Dense(32)(x))
            return optax.softmax_cross_entropy_with_integer_labels(
                nn.Dense(10)(h).astype(jnp.float32), y).mean()

    rng = np.random.default_rng(0)
    labels = rng.integers(0, 10, 2048).astype(np.int32)
    x = rng.normal(0, 0.3, size=(2048, 8, 8)).astype(np.float32)
    for i, y in enumerate(labels):
        x[i, y % 8, :] += 1.5
    ds = ArrayDataset(x, labels)

    def run(name, **kw):
        strat = DiLoCoStrategy(optim_spec=OptimSpec("adamw", lr=3e-3),
                               H=5, **kw)
        res = Trainer(MLP(), ds).fit(
            strategy=strat, num_nodes=4, max_steps=40, batch_size=8,
            minibatch_size=8, val_size=0, val_interval=0,
            show_progress=False, seed=5, log_dir=str(tmp_path),
            run_name=name)
        losses = [l for _, l in res.history["train_loss"]]
        return float(np.mean(losses[-5:]))

    dense = run("dense")
    int4 = run("int4", codec="int4")
    topk_ef = run("topk_ef", codec="topk", frac=0.05)
    topk_ablate = run("topk_ablate", codec="topk", frac=0.05,
                      error_feedback=False)
    # int4 + EF: within tolerance of the dense trajectory (measured
    # ~3e-4 apart at this scale; 0.05 absorbs seed-level noise without
    # letting a broken link through)
    assert abs(int4 - dense) < 0.05, (int4, dense)
    # ablation: the same top-k link without the residual visibly
    # diverges from its EF twin (measured ~0.8 vs ~1.6 here)
    assert topk_ablate > topk_ef + 0.3, (topk_ablate, topk_ef)
    # and the EF run still broadly converges while the ablated one is
    # far off the dense trajectory
    assert topk_ablate - dense > 2 * (topk_ef - dense)


def test_fit_rejects_unknown_network_preset():
    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from gym_tpu import Trainer
    from gym_tpu.data import ArrayDataset

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, batch, train=True):
            x, y = batch
            return optax.softmax_cross_entropy_with_integer_labels(
                nn.Dense(2)(x).astype(jnp.float32), y).mean()

    rng = np.random.default_rng(0)
    ds = ArrayDataset(rng.normal(size=(64, 4)).astype(np.float32),
                      rng.integers(0, 2, 64).astype(np.int32))
    with pytest.raises(ValueError, match="unknown topology preset"):
        Trainer(MLP(), ds).fit(
            strategy=SimpleReduceStrategy(), num_nodes=2, max_steps=2,
            batch_size=4, val_size=0, show_progress=False,
            network="not-a-preset", log_dir="/tmp/gym_tpu_never")
