"""Token streaming (ISSUE 13): the in-process half of the streaming
fleet — chunk-granular streams exactly equal to ``generate_fast``,
mid-stream failover SPLICE (the PR-8 exact-stream oracle upgraded to
streaming), client-disconnect cancellation at the chunk boundary, and
the metrics schema riders (``pid`` column, ``status=disconnected``,
``streams_active``, old-header tolerance)."""

import csv
import json
import os
import socket
import tempfile
import threading
import time

import numpy as np
import pytest

import jax

from gym_tpu.models.nanogpt import GPT, GPTConfig, generate_fast
from gym_tpu.serve.engine import InferenceEngine, SamplingParams
from gym_tpu.serve.metrics import HEADER, ServeMetrics, read_headline
from gym_tpu.serve.router import build_fleet
from gym_tpu.serve.scheduler import (RequestCancelledError,
                                     RequestStatus, Scheduler)


@pytest.fixture(scope="module")
def setup():
    cfg = GPTConfig(block_size=64, vocab_size=48, n_layer=2, n_head=2,
                    n_embd=32, dropout=0.0, bias=True)
    model = GPT(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int64),
                        train=False)["params"]
    return cfg, params


def _ref(params, cfg, prompt, n, **kw):
    return generate_fast(params, cfg, np.asarray(prompt)[None], n,
                         **kw)[0, len(prompt):].tolist()


# -- FleetRequest.stream --------------------------------------------------


def test_stream_chunks_concatenate_to_exact_generate_fast(setup):
    """Streamed chunks, concatenated, are byte-identical to the
    buffered result AND to ``generate_fast`` — and more than one chunk
    arrives (it is a stream, not a buffer)."""
    cfg, params = setup
    router = build_fleet(params, cfg, replicas=1, num_slots=2,
                         log=lambda *a, **k: None).start()
    try:
        prompt = [1, 2, 3, 4, 5, 6]
        ref = _ref(params, cfg, prompt, 16, temperature=0.9, top_k=7,
                   seed=3)
        fr = router.submit(prompt, SamplingParams(
            max_new_tokens=16, temperature=0.9, top_k=7, seed=3))
        got, chunks = [], 0
        for chunk in fr.stream(timeout=60):
            got.extend(chunk)
            chunks += 1
        assert got == ref
        assert chunks > 1
        assert fr.ttft_s is not None and fr.done_t is not None
    finally:
        router.close(drain_deadline_s=30)


def test_mid_stream_replica_kill_splices_exact(setup, tmp_path):
    """THE streaming splice oracle (in-process half): kill the serving
    replica after >= 4 tokens have been streamed — the concatenated
    stream the client saw is byte-identical to an uncontended run, the
    failover is recorded, and it fits the original deadline."""
    cfg, params = setup
    m = ServeMetrics(str(tmp_path))
    router = build_fleet(params, cfg, replicas=2, num_slots=2,
                         metrics=m, max_restarts=0,
                         log=lambda *a, **k: None).start()
    try:
        prompt = [1, 2, 3, 4, 5, 6]
        ref = _ref(params, cfg, prompt, 24, temperature=0.9, top_k=7,
                   seed=5)
        fr = router.submit(prompt, SamplingParams(
            max_new_tokens=24, temperature=0.9, top_k=7, seed=5),
            deadline_s=60.0)
        victim = fr.replica_id
        got, killed = [], False
        t0 = time.perf_counter()
        for chunk in fr.stream(timeout=60):
            got.extend(chunk)
            if not killed and len(got) >= 4:
                def boom(*a, **k):
                    raise RuntimeError("test: injected hard death")
                router.replicas[victim].scheduler.engine.step = boom
                killed = True
        assert killed, "stream finished before the kill landed"
        assert got == ref                       # no dupes, no gaps
        assert time.perf_counter() - t0 < 60.0  # inside the deadline
        assert fr.failovers == 1
        assert fr.replica_id != victim
        assert router.status()["failovers"] == 1
    finally:
        router.close(drain_deadline_s=30)
        m.close()


# -- scheduler.cancel (the disconnect primitive) --------------------------


def test_cancel_running_frees_slot_at_chunk_boundary(setup, tmp_path):
    cfg, params = setup
    m = ServeMetrics(str(tmp_path))
    sched = Scheduler(InferenceEngine(params, cfg, num_slots=1),
                      metrics=m.replica_view(0))
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    try:
        req = sched.submit([1, 2, 3], SamplingParams(max_new_tokens=48,
                                                     seed=0))
        toks, _ = req.wait_progress(0, timeout=30)
        assert toks, "no progress before cancel"
        assert sched.cancel(req) is True
        with pytest.raises(RequestCancelledError):
            req.result(timeout=30)
        assert req.status is RequestStatus.FAILED
        assert len(req.tokens) < 48
        # the slot is FREE: the next request runs to completion
        nxt = sched.submit([4, 5], SamplingParams(max_new_tokens=4,
                                                  seed=1))
        assert len(nxt.result(timeout=60)) == 4
        # a second cancel is a no-op on a resolved request
        assert sched.cancel(req) is False
    finally:
        stop.set()
        t.join(timeout=30)
        sched.shutdown(finish_running=False, deadline_s=0.0)
        m.close()
    head = read_headline(os.path.join(str(tmp_path), "serve.csv"))
    assert head["requests_disconnected"] == 1
    assert head["requests_failed"] == 0      # a disconnect is not a
    #                                          server failure
    assert head["requests_done"] == 1


def test_cancel_queued_fails_immediately(setup):
    cfg, params = setup
    sched = Scheduler(InferenceEngine(params, cfg, num_slots=1))
    # no driver running: the request stays queued
    req = sched.submit([1, 2, 3], SamplingParams(max_new_tokens=4))
    assert sched.cancel(req) is True
    with pytest.raises(RequestCancelledError):
        req.result(timeout=5)
    assert sched.queue_depth() == 0
    sched.shutdown(finish_running=False, deadline_s=0.0)


# -- HTTP streaming + disconnect regression -------------------------------


@pytest.fixture()
def http_server(setup):
    from gym_tpu.serve.__main__ import create_server
    cfg, params = setup
    handle = create_server(
        params, cfg, port=0, num_slots=2, replicas=1, warmup=False,
        metrics_dir=tempfile.mkdtemp(prefix="gym_tpu_stream_"))
    t = threading.Thread(target=handle.httpd.serve_forever, daemon=True)
    t.start()
    yield handle
    handle.close()


def _sse_events(port, payload, timeout=120):
    import urllib.request
    body = json.dumps(payload).encode()
    r = urllib.request.urlopen(urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", body,
        {"Content-Type": "application/json"}), timeout=timeout)
    assert r.headers["Content-Type"] == "text/event-stream"
    return [json.loads(line[6:]) for line in r
            if line.strip().startswith(b"data: ")]


def test_http_stream_true_is_chunked_and_exact(setup, http_server):
    cfg, params = setup
    ref = _ref(params, cfg, [1, 2, 3, 4, 5, 6], 16, temperature=0.9,
               top_k=7, seed=3)
    evs = _sse_events(http_server.port, {
        "prompt": [1, 2, 3, 4, 5, 6], "max_new_tokens": 16,
        "temperature": 0.9, "top_k": 7, "seed": 3, "stream": True})
    toks = [t for e in evs if not e.get("done")
            for t in e.get("tokens", [])]
    fin = evs[-1]
    assert fin.get("done") is True
    assert toks == ref
    assert fin["tokens_total"] == 16
    assert len(evs) > 2                      # chunked, not buffered
    # streamed TTFB ≡ first token: the reported ttft is a real number
    # well under the full latency
    assert fin["ttft_s"] is not None
    assert fin["latency_s"] > fin["ttft_s"]


def test_client_disconnect_after_two_chunks_is_recorded(http_server):
    """THE disconnect regression (ISSUE 13 satellite): a client that
    closes after 2 chunks → the request is cancelled at the next
    decode-chunk boundary, the slot freed, ``status=disconnected``
    lands in serve.csv (no traceback, not a failure), and the next
    request is served normally."""
    port = http_server.port
    s = socket.create_connection(("127.0.0.1", port))
    body = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 48,
                       "top_k": 4, "seed": 1, "stream": True}).encode()
    s.sendall(b"POST /generate HTTP/1.1\r\nHost: t\r\n"
              b"Content-Type: application/json\r\n"
              + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
    buf = b""
    while buf.count(b"data: ") < 2:
        chunk = s.recv(4096)
        assert chunk, "server closed before 2 chunks"
        buf += chunk
    s.close()                                # EPIPE on the next write
    deadline = time.monotonic() + 30
    head = {}
    while time.monotonic() < deadline:
        head = http_server.metrics.headline()
        if head.get("requests_disconnected", 0) >= 1:
            break
        time.sleep(0.1)
    assert head["requests_disconnected"] == 1, head
    assert head["streams_active"] == 0, head
    # slot freed: a fresh streamed request completes
    evs = _sse_events(port, {"prompt": [1, 2, 3], "max_new_tokens": 4,
                             "top_k": 4, "seed": 2, "stream": True})
    assert evs[-1].get("done") is True
    csv_path = os.path.join(http_server.metrics.path)
    with open(csv_path) as f:
        stats = [row["status"] for row in csv.DictReader(f)
                 if row["kind"] == "request"]
    assert "disconnected" in stats


# -- metrics schema riders ------------------------------------------------


def test_serve_csv_rows_carry_pid_and_headline_counts(tmp_path):
    m = ServeMetrics(str(tmp_path))
    view = m.replica_view(0, pid=4242)
    req = type("R", (), {
        "id": 1, "prompt": np.zeros(3, np.int32), "tokens": [1, 2, 3],
        "error": None, "exception": None, "ttft_s": 0.1,
        "avg_token_latency_s": 0.01})()
    view.request_done(req, queue_depth=0, active_slots=1)
    m.replica_spawned(replica_id=1, pid=4343)
    m.replica_retired(replica_id=1, pid=4343)
    m.stream_started()
    head = m.headline()
    assert head["replicas_spawned"] == 1
    assert head["replicas_retired"] == 1
    assert head["streams_active"] == 1
    m.stream_ended()
    assert m.headline()["streams_active"] == 0
    assert m.headline()["replicas"]["0"]["pid"] == 4242
    m.close()
    with open(os.path.join(str(tmp_path), "serve.csv")) as f:
        rows = list(csv.DictReader(f))
    assert rows[0]["pid"] == "4242"


def test_read_headline_tolerates_pre_pid_csv(tmp_path):
    """Pinned per repo convention: serve.csv files written BEFORE the
    pid/disconnect schema bump still aggregate — and new-schema files
    read back their disconnect counts."""
    old_header = [c for c in HEADER if c != "pid"]
    path = os.path.join(str(tmp_path), "serve.csv")
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(old_header)
        w.writerow(["0.5", "request", "0", "done", "0", "1", "3", "4",
                    "0.1", "0.01", "4", "8.0", "", "", "", "0",
                    "", "", "", "", ""])
        w.writerow(["0.9", "request", "1", "disconnected", "0", "1",
                    "3", "2", "0.1", "0.01", "6", "6.6", "", "", "",
                    "0", "", "", "", "", ""])
    head = read_headline(path)
    assert head["requests_done"] == 1
    assert head["requests_disconnected"] == 1
    assert head["requests_failed"] == 0
    assert head["replicas"]["0"]["requests_done"] == 1
